"""Multi-process (multi-host) execution context.

The TPU analog of the reference's one-logical-worker-per-parallel-group model
(reference components/src/dynamo/vllm/main.py:67: non-leader ranks of a TP
group idle inside the engine while rank 0 owns the endpoint): in JAX's
multi-controller model EVERY process must issue the same XLA programs over the
shared mesh, so "idling" followers are really a replay loop.

  - process 0 (leader) owns the control plane: discovery registration, the
    request plane endpoint, the scheduler, and every host-side decision.
  - processes 1..N-1 (followers) join the same ``jax.distributed`` cluster,
    hold their own handles of the globally-sharded state (params, KV caches,
    sampling tables), and replay each dispatch the leader broadcasts so the
    collective programs line up across processes.

The broadcast channel is a plain TCP fan-out (length-prefixed msgpack), NOT
the request plane: dispatch replay is a lockstep data-path concern, ordered
and point-to-point, with no discovery or retry semantics — the same reason
the reference runs NCCL alongside (not through) its NATS/etcd control plane.

Wire format: one frame per dispatch ``{"op": name, "a": [encoded args]}``.
numpy arrays ride as ``{"__nd__": [dtype.str, shape, bytes]}``; the sentinel
``{"__carry__": key}`` tells the follower to substitute its device-resident
carry state (decode horizon chaining never round-trips through the host —
engine/engine.py _dispatch_horizon).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import msgpack
import numpy as np

from .logging import get_logger

log = get_logger("runtime.multihost")

_LEN = struct.Struct("!I")
_TRACE = os.environ.get("DTPU_MH_TRACE") == "1"


def _trace(fmt: str, *args) -> None:
    if _TRACE:
        import sys

        print("[mh] " + (fmt % args), file=sys.stderr, flush=True)


@dataclass
class MultihostSpec:
    """Parsed ``--multihost coord:port,nprocs,proc_id[,control:port]``."""

    coordinator: str
    num_processes: int
    process_id: int
    control: str  # host:port the leader's control channel binds/dials

    @classmethod
    def parse(cls, text: str) -> "MultihostSpec":
        parts = text.split(",")
        if len(parts) < 3:
            raise ValueError(
                "--multihost wants coord_host:port,num_processes,process_id"
                "[,control_host:port]"
            )
        coord, nprocs, pid = parts[0], int(parts[1]), int(parts[2])
        if len(parts) > 3:
            control = parts[3]
        else:
            # default control port: coordinator port + 1 on the same host
            host, _, port = coord.rpartition(":")
            control = f"{host}:{int(port) + 1}"
        return cls(coord, nprocs, pid, control)


def _encode_arg(a: Any) -> Any:
    # dtype.name (not .str): extension dtypes like ml_dtypes' bfloat16 have
    # no char code — .str degrades to raw void ('|V2') which jit rejects —
    # but their registered NAME round-trips through np.dtype()
    if isinstance(a, np.ndarray):
        return {"__nd__": [a.dtype.name, list(a.shape), a.tobytes()]}
    if isinstance(a, (np.generic,)):  # 0-d scalar (np.int32(3), np.bool_(True))
        arr = np.asarray(a)
        return {"__nd0__": [arr.dtype.name, arr.tobytes()]}
    return a


def _decode_arg(a: Any) -> Any:
    if isinstance(a, dict):
        if "__nd__" in a:
            dt, shape, raw = a["__nd__"]
            return np.frombuffer(raw, dtype=np.dtype(dt)).reshape(shape)
        if "__nd0__" in a:
            dt, raw = a["__nd0__"]
            return np.frombuffer(raw, dtype=np.dtype(dt))[0]
    return a


class MultihostContext:
    """Owns the jax.distributed membership + the dispatch broadcast channel."""

    def __init__(self, spec: MultihostSpec):
        self.spec = spec
        self._socks: List[socket.socket] = []  # leader: one per follower
        self._sock: Optional[socket.socket] = None  # follower: to leader
        self._rbuf = b""
        self._lock = threading.Lock()
        self._closed = False
        self._router: Optional["MultihostRouter"] = None

    @property
    def router(self) -> "MultihostRouter":
        """The process-wide dispatch router (one per group membership)."""
        if self._router is None:
            self._router = MultihostRouter(self)
        return self._router

    # ------------------------------------------------------------ membership
    @property
    def is_leader(self) -> bool:
        return self.spec.process_id == 0

    @property
    def num_processes(self) -> int:
        return self.spec.num_processes

    def initialize_jax(self) -> None:
        """Join the jax.distributed cluster (must run before device use)."""
        import jax

        jax.distributed.initialize(
            coordinator_address=self.spec.coordinator,
            num_processes=self.spec.num_processes,
            process_id=self.spec.process_id,
        )
        log.info(
            "joined jax cluster as process %d/%d (%d local / %d global devices)",
            self.spec.process_id, self.spec.num_processes,
            jax.local_device_count(), jax.device_count(),
        )

    # --------------------------------------------------------- control plane
    def start_control(self, timeout_s: float = 60.0) -> None:
        """Leader: accept one connection per follower. Follower: dial."""
        host, _, port = self.spec.control.rpartition(":")
        port = int(port)
        if self.is_leader:
            srv = socket.create_server((host, port), reuse_port=False)
            deadline = time.monotonic() + timeout_s
            try:
                pending = self.spec.num_processes - 1
                seen: Dict[int, socket.socket] = {}
                while len(seen) < pending:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"only {len(seen)}/{pending} followers dialed in"
                        )
                    srv.settimeout(remaining)
                    conn, _addr = srv.accept()
                    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    # bound the hello read too: a stray connection (port
                    # scanner, dead follower) must not wedge startup — drop
                    # it and keep accepting
                    conn.settimeout(5.0)
                    try:
                        hello = b""
                        while len(hello) < 4:
                            part = conn.recv(4 - len(hello))
                            if not part:
                                raise ConnectionError("hello truncated")
                            hello += part
                        (pid,) = _LEN.unpack(hello)
                    except (OSError, ConnectionError) as e:
                        log.warning("control dial-in rejected: %s", e)
                        conn.close()
                        continue
                    conn.settimeout(None)  # dispatch gaps are unbounded
                    seen[pid] = conn
                # deterministic fan-out order
                self._socks = [seen[k] for k in sorted(seen)]
            finally:
                srv.close()
        else:
            deadline = time.monotonic() + timeout_s
            last: Optional[Exception] = None
            while time.monotonic() < deadline:
                try:
                    s = socket.create_connection((host, port), timeout=5.0)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    # connect timeout must NOT linger: recv() blocks across
                    # arbitrarily long idle gaps between dispatches
                    s.settimeout(None)
                    s.sendall(_LEN.pack(self.spec.process_id))
                    self._sock = s
                    return
                except OSError as e:  # leader not up yet
                    last = e
                    time.sleep(0.2)
            raise TimeoutError(f"control channel dial failed: {last}")

    def broadcast(self, op: str, args: List[Any]) -> None:
        """Leader: fan one dispatch out to every follower, in order.

        Fails FAST on the first dead socket: the leader will not execute the
        op either, so delivering the frame to later survivors would only
        push them into a collective the leader (and the dead peer) never
        join. Survivors that already received it may wedge mid-collective —
        unrecoverable in-process (XLA collectives have no cancel); the
        jax.distributed coordination-service timeout reaps them, and the
        follower-death teardown (watch_followers → group close → supervisor
        restart) handles the rest.
        """
        payload = msgpack.packb(
            {"op": op, "a": [_encode_arg(a) for a in args]}, use_bin_type=True
        )
        frame = _LEN.pack(len(payload)) + payload
        with self._lock:
            for s in self._socks:
                try:
                    s.sendall(frame)
                except OSError as e:
                    raise ConnectionError(
                        f"follower unreachable during broadcast of {op!r}: {e}"
                    ) from e

    def watch_followers(self, on_death: Callable[[], None]) -> None:
        """Leader: detect follower death between dispatches.

        Followers never send after their hello, so a readable control socket
        means EOF (process died / connection reset). One background thread
        select()s on all follower sockets; the first death fires ``on_death``
        once and the thread exits — the group is unrecoverable (the dead
        process held mesh shards; any later collective would hang), so the
        caller's job is to deregister and exit for a supervisor restart.
        Reference analog: vllm engine_monitor killing the worker when an
        engine rank dies (components/src/dynamo/vllm/engine_monitor.py).
        """
        import select

        def run() -> None:
            socks = list(self._socks)
            while not self._closed and socks:
                try:
                    r, _, x = select.select(socks, [], socks, 1.0)
                except (OSError, ValueError):
                    return  # sockets closed under us: normal group stop
                dead = False
                for s in set(r) | set(x):
                    try:
                        if not s.recv(1):
                            dead = True
                    except OSError:
                        dead = True
                if dead:
                    if not self._closed:
                        log.error("multihost follower died; tearing down group")
                        on_death()
                    return

        threading.Thread(target=run, daemon=True, name="mh-follower-watch").start()

    def recv(self) -> Dict[str, Any]:
        """Follower: block for the next dispatch frame."""
        assert self._sock is not None
        while True:
            if len(self._rbuf) >= 4:
                (n,) = _LEN.unpack(self._rbuf[:4])
                if len(self._rbuf) >= 4 + n:
                    raw = self._rbuf[4 : 4 + n]
                    self._rbuf = self._rbuf[4 + n :]
                    msg = msgpack.unpackb(raw, raw=False)
                    msg["a"] = [_decode_arg(a) for a in msg.get("a", [])]
                    return msg
            chunk = self._sock.recv(1 << 20)
            if not chunk:
                raise ConnectionError("control channel closed by leader")
            self._rbuf += chunk

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.is_leader:
            try:
                self.broadcast("__stop__", [])
            except OSError:
                pass
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def shutdown_jax(self) -> None:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:  # already torn down / never initialized
            pass


CARRY = "__carry__"


class MultihostRouter:
    """Process-level dispatch fabric: ONE broadcast channel, one total order,
    any number of engine replay tables (dp ranks, disagg roles) multiplexed
    by a namespace prefix on the op name (``dp1:decode``).

    Dispatches come from more than one thread (each engine's step executor
    AND the asyncio loop thread); broadcast + local XLA dispatch happen under
    ONE process-wide lock so every process executes the same total order —
    jit returns after async-enqueue, so the hold is ~ms.
    """

    def __init__(self, mh: MultihostContext):
        self.mh = mh
        self._tables: Dict[str, "MultihostOps"] = {}
        self._closed = False
        self.dispatch_lock = threading.Lock()

    def table(
        self,
        state_get: Dict[str, Callable[[], Any]],
        state_set: Dict[str, Callable[[Any], None]],
        ns: str = "",
    ) -> "MultihostOps":
        if ns in self._tables:
            raise ValueError(f"multihost namespace {ns!r} already registered")
        ops = MultihostOps(self, ns, state_get, state_set)
        self._tables[ns] = ops
        return ops

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop the group, serialized against in-flight dispatches.

        Taking the dispatch lock means any dispatch racing this close either
        fully broadcast+executed BEFORE the __stop__ frame (the follower
        replays it, then exits) or is rejected after — a late collective
        executed by the leader alone would block forever waiting for peers.
        Idempotent: every engine of a dp group calls it on stop.

        The lock acquire is BOUNDED: on a follower-death teardown a dispatch
        may be wedged mid-broadcast holding the lock; after ``timeout_s`` we
        close anyway (slamming the sockets makes the wedged sendall raise,
        failing that dispatch — correct in a death scenario).
        """
        got = self.dispatch_lock.acquire(timeout=timeout_s)
        try:
            if self._closed:
                return
            self._closed = True
            self.mh.close()
        finally:
            if got:
                self.dispatch_lock.release()

    @property
    def closed(self) -> bool:
        return self._closed

    def follow(self) -> None:
        """Follower body: replay dispatches (all namespaces) until stop."""
        while True:
            msg = self.mh.recv()
            op = msg["op"]
            _trace("follower: recv %s", op)
            if op == "__stop__":
                return
            ns, _, name = op.rpartition(":")
            self._tables[ns].replay(name, msg)


class MultihostOps:
    """Per-engine dispatch replay table (one namespace of the router).

    Each op is registered with:
      - ``state_in``:  {arg_pos: state_name} — args the follower substitutes
        with its OWN handle of the shared global array (params, caches, ...)
      - ``state_out``: {out_pos: state_name} — outputs both sides store back
        (donated caches, penalty tables, the decode carry)
      - ``carry_in``:  {arg_pos: state_name} — args that are EITHER a host
        resync value (numpy → broadcast by value) or the device carry of the
        previous dispatch (jax.Array → broadcast as a carry sentinel)

    The leader-side wrapper converts every non-state arg to host numpy before
    both the broadcast AND the local call: in multi-controller JAX a committed
    single-device array cannot feed a mesh-spanning computation, while plain
    numpy shards consistently on every process.
    """

    def __init__(self, router: MultihostRouter, ns: str,
                 state_get: Dict[str, Callable[[], Any]],
                 state_set: Dict[str, Callable[[Any], None]]):
        self.router = router
        self.ns = ns
        self.mh = router.mh
        self._get = state_get
        self._set = state_set
        self._ops: Dict[str, tuple] = {}
        self._carry: Dict[str, Any] = {}

    def close(self) -> None:
        self.router.close()

    def register(self, name: str, fn: Callable, state_in: Dict[int, str],
                 state_out: Dict[int, str], carry_in: Optional[Dict[int, str]] = None):
        self._ops[name] = (fn, state_in, state_out, carry_in or {})

    # ------------------------------------------------------------- leader side
    def leader_fn(self, name: str) -> Callable:
        fn, state_in, state_out, carry_in = self._ops[name]
        mh = self.mh
        wire_name = f"{self.ns}:{name}"

        def dispatch(*args):
            import jax

            send: List[Any] = []
            call: List[Any] = list(args)
            for i, a in enumerate(args):
                if i in state_in:
                    continue  # follower substitutes its own handle
                if i in carry_in and isinstance(a, jax.Array):
                    send.append({CARRY: carry_in[i]})
                    continue
                host = (
                    a if isinstance(a, (int, float, bool, type(None)))
                    else np.asarray(a)
                )
                send.append(
                    _encode_arg(host)
                    if isinstance(host, (np.ndarray, np.generic)) else host
                )
                call[i] = host
            with self.router.dispatch_lock:
                if self.router.closed:
                    raise RuntimeError(
                        f"multihost group stopped; dropping dispatch {name!r}"
                    )
                _trace("leader: broadcast %s", wire_name)
                mh.broadcast(wire_name, send)
                out = fn(*call)
                _trace("leader: dispatched %s", wire_name)
                return out

        return dispatch

    # ----------------------------------------------------------- follower side
    def replay(self, op: str, msg: Dict[str, Any]) -> None:
        fn, state_in, state_out, carry_in = self._ops[op]
        data = msg["a"]
        n_args = len(data) + len(state_in)
        args: List[Any] = [None] * n_args
        it = iter(data)
        for i in range(n_args):
            if i in state_in:
                args[i] = self._get[state_in[i]]()
            else:
                a = next(it)
                if isinstance(a, dict) and CARRY in a:
                    args[i] = self._carry[a[CARRY]]
                else:
                    args[i] = a
        out = fn(*args)
        _trace("follower: executed %s:%s", self.ns, op)
        outs = out if isinstance(out, tuple) else (out,)
        for pos, sname in state_out.items():
            if sname.startswith("carry_"):
                self._carry[sname] = outs[pos]
            else:
                self._set[sname](outs[pos])

    def follow(self) -> None:
        """Single-table convenience: replay until stop (delegates to the
        router; valid when this is the only namespace)."""
        self.router.follow()
