"""Multi-process (multi-host) execution context.

The TPU analog of the reference's one-logical-worker-per-parallel-group model
(reference components/src/dynamo/vllm/main.py:67: non-leader ranks of a TP
group idle inside the engine while rank 0 owns the endpoint): in JAX's
multi-controller model EVERY process must issue the same XLA programs over the
shared mesh, so "idling" followers are really a replay loop.

  - process 0 (leader) owns the control plane: discovery registration, the
    request plane endpoint, the scheduler, and every host-side decision.
  - processes 1..N-1 (followers) join the same ``jax.distributed`` cluster,
    hold their own handles of the globally-sharded state (params, KV caches,
    sampling tables), and replay each dispatch the leader broadcasts so the
    collective programs line up across processes.

The broadcast channel is a plain TCP fan-out (length-prefixed msgpack), NOT
the request plane: dispatch replay is a lockstep data-path concern, ordered
and point-to-point, with no discovery or retry semantics — the same reason
the reference runs NCCL alongside (not through) its NATS/etcd control plane.

Wire format: one frame per dispatch ``{"op": name, "a": [encoded args]}``.
numpy arrays ride as ``{"__nd__": [dtype.str, shape, bytes]}``; the sentinel
``{"__carry__": key}`` tells the follower to substitute its device-resident
carry state (decode horizon chaining never round-trips through the host —
engine/engine.py _dispatch_horizon).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import msgpack
import numpy as np

from .logging import get_logger

log = get_logger("runtime.multihost")

_LEN = struct.Struct("!I")
_TRACE = os.environ.get("DTPU_MH_TRACE") == "1"


def _trace(fmt: str, *args) -> None:
    if _TRACE:
        import sys

        print("[mh] " + (fmt % args), file=sys.stderr, flush=True)


@dataclass
class MultihostSpec:
    """Parsed ``--multihost coord:port,nprocs,proc_id[,control:port]``."""

    coordinator: str
    num_processes: int
    process_id: int
    control: str  # host:port the leader's control channel binds/dials

    @classmethod
    def parse(cls, text: str) -> "MultihostSpec":
        parts = text.split(",")
        if len(parts) < 3:
            raise ValueError(
                "--multihost wants coord_host:port,num_processes,process_id"
                "[,control_host:port]"
            )
        coord, nprocs, pid = parts[0], int(parts[1]), int(parts[2])
        if len(parts) > 3:
            control = parts[3]
        else:
            # default control port: coordinator port + 1 on the same host
            host, _, port = coord.rpartition(":")
            control = f"{host}:{int(port) + 1}"
        return cls(coord, nprocs, pid, control)


def _encode_arg(a: Any) -> Any:
    # dtype.name (not .str): extension dtypes like ml_dtypes' bfloat16 have
    # no char code — .str degrades to raw void ('|V2') which jit rejects —
    # but their registered NAME round-trips through np.dtype()
    if isinstance(a, np.ndarray):
        return {"__nd__": [a.dtype.name, list(a.shape), a.tobytes()]}
    if isinstance(a, (np.generic,)):  # 0-d scalar (np.int32(3), np.bool_(True))
        arr = np.asarray(a)
        return {"__nd0__": [arr.dtype.name, arr.tobytes()]}
    return a


def _decode_arg(a: Any) -> Any:
    if isinstance(a, dict):
        if "__nd__" in a:
            dt, shape, raw = a["__nd__"]
            return np.frombuffer(raw, dtype=np.dtype(dt)).reshape(shape)
        if "__nd0__" in a:
            dt, raw = a["__nd0__"]
            return np.frombuffer(raw, dtype=np.dtype(dt))[0]
    return a


class MultihostContext:
    """Owns the jax.distributed membership + the dispatch broadcast channel."""

    def __init__(self, spec: MultihostSpec):
        self.spec = spec
        self._socks: List[socket.socket] = []  # leader: one per follower
        self._sock: Optional[socket.socket] = None  # follower: to leader
        self._rbuf = b""
        self._lock = threading.Lock()
        self._closed = False

    # ------------------------------------------------------------ membership
    @property
    def is_leader(self) -> bool:
        return self.spec.process_id == 0

    @property
    def num_processes(self) -> int:
        return self.spec.num_processes

    def initialize_jax(self) -> None:
        """Join the jax.distributed cluster (must run before device use)."""
        import jax

        jax.distributed.initialize(
            coordinator_address=self.spec.coordinator,
            num_processes=self.spec.num_processes,
            process_id=self.spec.process_id,
        )
        log.info(
            "joined jax cluster as process %d/%d (%d local / %d global devices)",
            self.spec.process_id, self.spec.num_processes,
            jax.local_device_count(), jax.device_count(),
        )

    # --------------------------------------------------------- control plane
    def start_control(self, timeout_s: float = 60.0) -> None:
        """Leader: accept one connection per follower. Follower: dial."""
        host, _, port = self.spec.control.rpartition(":")
        port = int(port)
        if self.is_leader:
            srv = socket.create_server((host, port), reuse_port=False)
            deadline = time.monotonic() + timeout_s
            try:
                pending = self.spec.num_processes - 1
                seen: Dict[int, socket.socket] = {}
                while len(seen) < pending:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"only {len(seen)}/{pending} followers dialed in"
                        )
                    srv.settimeout(remaining)
                    conn, _addr = srv.accept()
                    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    # bound the hello read too: a stray connection (port
                    # scanner, dead follower) must not wedge startup — drop
                    # it and keep accepting
                    conn.settimeout(5.0)
                    try:
                        hello = b""
                        while len(hello) < 4:
                            part = conn.recv(4 - len(hello))
                            if not part:
                                raise ConnectionError("hello truncated")
                            hello += part
                        (pid,) = _LEN.unpack(hello)
                    except (OSError, ConnectionError) as e:
                        log.warning("control dial-in rejected: %s", e)
                        conn.close()
                        continue
                    conn.settimeout(None)  # dispatch gaps are unbounded
                    seen[pid] = conn
                # deterministic fan-out order
                self._socks = [seen[k] for k in sorted(seen)]
            finally:
                srv.close()
        else:
            deadline = time.monotonic() + timeout_s
            last: Optional[Exception] = None
            while time.monotonic() < deadline:
                try:
                    s = socket.create_connection((host, port), timeout=5.0)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    # connect timeout must NOT linger: recv() blocks across
                    # arbitrarily long idle gaps between dispatches
                    s.settimeout(None)
                    s.sendall(_LEN.pack(self.spec.process_id))
                    self._sock = s
                    return
                except OSError as e:  # leader not up yet
                    last = e
                    time.sleep(0.2)
            raise TimeoutError(f"control channel dial failed: {last}")

    def broadcast(self, op: str, args: List[Any]) -> None:
        """Leader: fan one dispatch out to every follower, in order."""
        payload = msgpack.packb(
            {"op": op, "a": [_encode_arg(a) for a in args]}, use_bin_type=True
        )
        frame = _LEN.pack(len(payload)) + payload
        with self._lock:
            for s in self._socks:
                s.sendall(frame)

    def recv(self) -> Dict[str, Any]:
        """Follower: block for the next dispatch frame."""
        assert self._sock is not None
        while True:
            if len(self._rbuf) >= 4:
                (n,) = _LEN.unpack(self._rbuf[:4])
                if len(self._rbuf) >= 4 + n:
                    raw = self._rbuf[4 : 4 + n]
                    self._rbuf = self._rbuf[4 + n :]
                    msg = msgpack.unpackb(raw, raw=False)
                    msg["a"] = [_decode_arg(a) for a in msg.get("a", [])]
                    return msg
            chunk = self._sock.recv(1 << 20)
            if not chunk:
                raise ConnectionError("control channel closed by leader")
            self._rbuf += chunk

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.is_leader:
            try:
                self.broadcast("__stop__", [])
            except OSError:
                pass
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def shutdown_jax(self) -> None:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:  # already torn down / never initialized
            pass


CARRY = "__carry__"


class MultihostOps:
    """Per-engine dispatch replay table.

    Each op is registered with:
      - ``state_in``:  {arg_pos: state_name} — args the follower substitutes
        with its OWN handle of the shared global array (params, caches, ...)
      - ``state_out``: {out_pos: state_name} — outputs both sides store back
        (donated caches, penalty tables, the decode carry)
      - ``carry_in``:  {arg_pos: state_name} — args that are EITHER a host
        resync value (numpy → broadcast by value) or the device carry of the
        previous dispatch (jax.Array → broadcast as a carry sentinel)

    The leader-side wrapper converts every non-state arg to host numpy before
    both the broadcast AND the local call: in multi-controller JAX a committed
    single-device array cannot feed a mesh-spanning computation, while plain
    numpy shards consistently on every process.
    """

    def __init__(self, mh: MultihostContext, state_get: Dict[str, Callable[[], Any]],
                 state_set: Dict[str, Callable[[Any], None]]):
        self.mh = mh
        self._get = state_get
        self._set = state_set
        self._ops: Dict[str, tuple] = {}
        self._carry: Dict[str, Any] = {}
        self._closed = False
        # dispatches come from more than one thread (the engine's step
        # executor AND its asyncio loop thread); broadcast + local XLA
        # dispatch happen under ONE lock so every process executes the same
        # total order — jit returns after async-enqueue, so the hold is ~ms
        self._dispatch_lock = threading.Lock()

    def close(self) -> None:
        """Stop the group, serialized against in-flight dispatches.

        Taking the dispatch lock means any dispatch racing this close either
        fully broadcast+executed BEFORE the __stop__ frame (the follower
        replays it, then exits) or is rejected after — a late collective
        executed by the leader alone would block forever waiting for peers.
        """
        with self._dispatch_lock:
            self._closed = True
            self.mh.close()

    def register(self, name: str, fn: Callable, state_in: Dict[int, str],
                 state_out: Dict[int, str], carry_in: Optional[Dict[int, str]] = None):
        self._ops[name] = (fn, state_in, state_out, carry_in or {})

    # ------------------------------------------------------------- leader side
    def leader_fn(self, name: str) -> Callable:
        fn, state_in, state_out, carry_in = self._ops[name]
        mh = self.mh

        def dispatch(*args):
            import jax

            send: List[Any] = []
            call: List[Any] = list(args)
            for i, a in enumerate(args):
                if i in state_in:
                    continue  # follower substitutes its own handle
                if i in carry_in and isinstance(a, jax.Array):
                    send.append({CARRY: carry_in[i]})
                    continue
                host = (
                    a if isinstance(a, (int, float, bool, type(None)))
                    else np.asarray(a)
                )
                send.append(
                    _encode_arg(host)
                    if isinstance(host, (np.ndarray, np.generic)) else host
                )
                call[i] = host
            with self._dispatch_lock:
                if self._closed:
                    raise RuntimeError(
                        f"multihost group stopped; dropping dispatch {name!r}"
                    )
                _trace("leader: broadcast %s", name)
                mh.broadcast(name, send)
                out = fn(*call)
                _trace("leader: dispatched %s", name)
                return out

        return dispatch

    # ----------------------------------------------------------- follower side
    def follow(self) -> None:
        """Replay dispatches until the leader says stop (or hangs up)."""
        while True:
            msg = self.mh.recv()
            op = msg["op"]
            _trace("follower: recv %s", op)
            if op == "__stop__":
                return
            fn, state_in, state_out, carry_in = self._ops[op]
            data = msg["a"]
            n_args = len(data) + len(state_in)
            args: List[Any] = [None] * n_args
            it = iter(data)
            for i in range(n_args):
                if i in state_in:
                    args[i] = self._get[state_in[i]]()
                else:
                    a = next(it)
                    if isinstance(a, dict) and CARRY in a:
                        args[i] = self._carry[a[CARRY]]
                    else:
                        args[i] = a
            out = fn(*args)
            _trace("follower: executed %s", op)
            outs = out if isinstance(out, tuple) else (out,)
            for pos, sname in state_out.items():
                if sname.startswith("carry_"):
                    self._carry[sname] = outs[pos]
                else:
                    self._set[sname](outs[pos])
