"""Distributed tracing: W3C traceparent propagation + OTLP-shaped export.

Analog of the reference's OTLP tracing stack (lib/runtime/src/logging.rs:
72-97 — tracing-subscriber + opentelemetry-otlp with traceparent
extraction/injection, logging.rs:206-270). TPU-first design notes: spans are
plain host-side bookkeeping (never traced under jit); propagation rides the
same channels the reference uses — HTTP headers in the frontend, request
annotations on the request plane.

Exporters:
- ``JsonlExporter``   — OTLP-shaped span dicts to a JSONL file (the air-gapped
                        default; collectors can tail it).
- ``OtlpHttpExporter``— OTLP/HTTP JSON to a configured collector endpoint
                        (``DYN_OTLP_ENDPOINT``; the reference defaults to
                        localhost:4317 gRPC — we speak OTLP/HTTP instead,
                        one POST per batch, best-effort).
- ``InMemoryExporter``— tests.

Span context propagates across ``asyncio`` tasks via ``contextvars``, so an
engine's nested spans parent correctly without explicit plumbing.
"""

from __future__ import annotations

import atexit
import contextvars
import dataclasses
import json
import os
import queue
import secrets
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .logging import get_logger

log = get_logger("tracing")

_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "dtpu_current_span", default=None
)

TRACEPARENT_VERSION = "00"
SAMPLED_FLAG = "01"


def new_trace_id() -> str:
    return secrets.token_hex(16)


def new_span_id() -> str:
    return secrets.token_hex(8)


def parse_traceparent(header: str) -> Tuple[Optional[str], Optional[str]]:
    """``00-<trace_id>-<parent_span_id>-<flags>`` -> (trace_id, parent_id).

    Malformed headers yield (None, None) — a bad client header must never
    fail a request (reference logging.rs:213-230 same tolerance)."""
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None, None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None, None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None, None
    return trace_id.lower(), span_id.lower()


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"{TRACEPARENT_VERSION}-{trace_id}-{span_id}-{SAMPLED_FLAG}"


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    start_ns: int = 0
    end_ns: int = 0
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)
    status: str = "OK"
    _tracer: Optional["Tracer"] = dataclasses.field(default=None, repr=False)
    _token: Any = dataclasses.field(default=None, repr=False)

    def set(self, **attrs: Any) -> "Span":
        self.attributes.update(attrs)
        return self

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    # -- context manager ------------------------------------------------------
    def __enter__(self) -> "Span":
        self.start_ns = time.time_ns()
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end_ns = time.time_ns()
        if exc_type is not None:
            self.status = "ERROR"
            self.attributes.setdefault("exception", repr(exc))
        if self._token is not None:
            try:
                _current_span.reset(self._token)
            except ValueError:
                # async generators may exit in a different Context than the
                # one that entered the span; the var is task-local anyway
                pass
        if self._tracer is not None:
            self._tracer._finish(self)

    def to_otlp(self) -> Dict[str, Any]:
        """One span in OTLP/JSON shape (the unit inside scopeSpans.spans)."""
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            **({"parentSpanId": self.parent_id} if self.parent_id else {}),
            "name": self.name,
            "kind": 1,
            "startTimeUnixNano": str(self.start_ns),
            "endTimeUnixNano": str(self.end_ns),
            "attributes": [
                {"key": k, "value": _otlp_value(v)}
                for k, v in self.attributes.items()
            ],
            "status": {"code": 2 if self.status == "ERROR" else 1},
        }


def _otlp_value(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def current_span() -> Optional[Span]:
    return _current_span.get()


def current_traceparent() -> Optional[str]:
    sp = _current_span.get()
    return sp.traceparent() if sp is not None else None


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


class InMemoryExporter:
    def __init__(self):
        self.spans: List[Span] = []

    def export(self, spans: List[Span]) -> None:
        self.spans.extend(spans)


class JsonlExporter:
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def export(self, spans: List[Span]) -> None:
        with self._lock, open(self.path, "a") as f:
            for sp in spans:
                f.write(json.dumps(sp.to_otlp()) + "\n")


class OtlpHttpExporter:
    """OTLP/HTTP JSON POST to ``<endpoint>/v1/traces``; best-effort, never
    raises into the request path.

    The POST runs on a dedicated daemon thread behind a bounded queue:
    ``export()`` only enqueues, so a slow/unreachable collector costs the
    caller nothing (it used to block the finishing span's thread for up to
    ``timeout_s``). When the queue is full the batch is dropped, counted in
    ``dropped_spans``. ``flush()`` waits for queued batches to drain —
    registered via ``atexit`` so a short-lived process's tail batch still
    ships without any further span triggering a time-based flush."""

    def __init__(self, endpoint: str, service_name: str = "dynamo_tpu",
                 timeout_s: float = 2.0, queue_max: int = 64):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.timeout_s = timeout_s
        self.dropped_spans = 0
        self._q: "queue.Queue[Optional[List[Span]]]" = queue.Queue(maxsize=queue_max)
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()
        atexit.register(self.flush)

    def _ensure_worker(self) -> None:
        with self._worker_lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._run, name="dtpu-otlp-export", daemon=True
                )
                self._worker.start()

    def _run(self) -> None:
        while True:
            batch = self._q.get()
            try:
                if batch:
                    self._post(batch)
            finally:
                self._q.task_done()

    def export(self, spans: List[Span]) -> None:
        self._ensure_worker()
        try:
            self._q.put_nowait(list(spans))
        except queue.Full:
            self.dropped_spans += len(spans)
            log.debug(
                "otlp export queue full (dropping %d spans, %d lifetime)",
                len(spans), self.dropped_spans,
            )

    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Block until queued batches are posted (bounded by ``timeout_s``)."""
        deadline = time.monotonic() + (
            self.timeout_s if timeout_s is None else timeout_s
        )
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._q.all_tasks_done.wait(remaining)

    def _post(self, spans: List[Span]) -> None:
        body = json.dumps({
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": self.service_name},
                }]},
                "scopeSpans": [{
                    "scope": {"name": "dynamo_tpu.tracing"},
                    "spans": [sp.to_otlp() for sp in spans],
                }],
            }]
        }).encode()
        try:
            import urllib.request

            req = urllib.request.Request(
                self.endpoint + "/v1/traces", data=body,
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=self.timeout_s).read()
        except Exception as e:
            log.debug("otlp export failed (dropping %d spans): %r", len(spans), e)


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


class Tracer:
    """Creates spans, batches finished ones, hands them to the exporter.

    Flushing is size/time-triggered on the caller's thread (no background
    task to leak); ``flush()`` forces the rest out — call it on shutdown."""

    def __init__(self, exporter=None, service_name: str = "dynamo_tpu",
                 batch_size: int = 64, flush_interval_s: float = 5.0):
        self.exporter = exporter
        self.service_name = service_name
        self.batch_size = batch_size
        self.flush_interval_s = flush_interval_s
        self._buf: List[Span] = []
        self._lock = threading.Lock()
        self._last_flush = time.monotonic()

    @classmethod
    def from_env(cls, service_name: str = "dynamo_tpu") -> "Tracer":
        """DTPU_OTLP_ENDPOINT -> OTLP/HTTP; DTPU_TRACE_JSONL -> file; else
        tracing is a no-op (spans still propagate context). The DYN_-prefixed
        spellings are accepted as aliases (the reference's catalog prefix)."""
        from .config import ENV_OTLP_ENDPOINT, ENV_TRACE_JSONL

        endpoint = (
            os.environ.get(ENV_OTLP_ENDPOINT) or os.environ.get("DYN_OTLP_ENDPOINT", "")
        )
        jsonl = (
            os.environ.get(ENV_TRACE_JSONL) or os.environ.get("DYN_TRACE_JSONL", "")
        )
        if endpoint:
            return cls(OtlpHttpExporter(endpoint, service_name), service_name)
        if jsonl:
            return cls(JsonlExporter(jsonl), service_name)
        return cls(None, service_name)

    @property
    def enabled(self) -> bool:
        return self.exporter is not None

    def span(
        self,
        name: str,
        traceparent: Optional[str] = None,
        **attrs: Any,
    ) -> Span:
        """New span. Parenting precedence: explicit ``traceparent`` header >
        ambient contextvar > fresh trace root."""
        trace_id = parent_id = None
        if traceparent:
            trace_id, parent_id = parse_traceparent(traceparent)
        if trace_id is None:
            amb = _current_span.get()
            if amb is not None:
                trace_id, parent_id = amb.trace_id, amb.span_id
        return Span(
            name=name,
            trace_id=trace_id or new_trace_id(),
            span_id=new_span_id(),
            parent_id=parent_id,
            attributes=dict(attrs),
            _tracer=self,
        )

    def emit(
        self,
        name: str,
        start_ns: int,
        end_ns: int,
        traceparent: Optional[str] = None,
        status: str = "OK",
        **attrs: Any,
    ) -> Span:
        """A finished span with explicit timestamps. Engine-loop milestones
        (queue/prefill/decode phases) are observed after the fact from
        per-request timestamps, not wrapped in a context manager — this is
        the export path for those. Does not touch the ambient contextvar."""
        trace_id = parent_id = None
        if traceparent:
            trace_id, parent_id = parse_traceparent(traceparent)
        if trace_id is None:
            amb = _current_span.get()
            if amb is not None:
                trace_id, parent_id = amb.trace_id, amb.span_id
        span = Span(
            name=name,
            trace_id=trace_id or new_trace_id(),
            span_id=new_span_id(),
            parent_id=parent_id,
            start_ns=start_ns,
            end_ns=end_ns,
            attributes=dict(attrs),
            status=status,
        )
        self._finish(span)
        return span

    def _finish(self, span: Span) -> None:
        if self.exporter is None:
            return
        flush_now = False
        with self._lock:
            self._buf.append(span)
            if (
                len(self._buf) >= self.batch_size
                or time.monotonic() - self._last_flush > self.flush_interval_s
            ):
                flush_now = True
        if flush_now:
            self.flush()

    def flush(self) -> None:
        """Hand the buffered batch to the exporter. Non-blocking (the OTLP
        exporter enqueues to its worker thread) — safe on the request path."""
        with self._lock:
            batch, self._buf = self._buf, []
            self._last_flush = time.monotonic()
        if batch and self.exporter is not None:
            self.exporter.export(batch)

    def shutdown(self) -> None:
        """flush() plus a bounded wait for the exporter's queue to drain —
        the process-exit path (a plain flush would enqueue the tail batch
        and then let the daemon thread die with it unsent)."""
        self.flush()
        drain = getattr(self.exporter, "flush", None)
        if drain is not None:
            drain()


_global_tracer: Optional[Tracer] = None


def get_tracer() -> Tracer:
    global _global_tracer
    if _global_tracer is None:
        _global_tracer = Tracer.from_env()
        if _global_tracer.enabled:
            # the tail batch of a short-lived process (worker smoke run,
            # bench) must not die in the buffer; atexit LIFO runs this
            # before the exporter's own queue-drain hook
            atexit.register(_global_tracer.shutdown)
    return _global_tracer


def set_tracer(tracer: Optional[Tracer]) -> None:
    global _global_tracer
    _global_tracer = tracer
