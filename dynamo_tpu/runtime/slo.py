"""SLO accounting plane: per-class SLA targets, rolling attainment, burn rate.

The serving path knows *what happened* to a request (PR 3's milestone
timestamps) but not *what was promised*: nothing carries an SLA class, so
attainment math lives as ad-hoc percentile code in scenario scripts
(profiler/loadgen.py, sim/scenarios.py) and the planner scales on raw load
instead of on whether promises are being kept. This module is the one source
of truth for both halves:

- **The promise** — ``SlaSpec``: a named class (``interactive`` /
  ``standard`` / ``batch``, extensible via ``DTPU_SLA_CLASSES``) with TTFT /
  ITL targets and an optional e2e deadline. The HTTP frontend resolves a
  request's class (request ``sla`` field > ``x-dtpu-sla`` header > default),
  applies per-model overrides from the model card's runtime_config, and
  stamps the spec into the request-plane annotation (``ANNOTATION_SLA``)
  exactly like the traceparent — router, prefill router, engine and flight
  recorder all read the same dict.

- **The ledger** — ``SloAccountant``: per-``(model, sla_class)`` rolling
  attainment over 1m/5m/1h windows plus a cumulative ``total`` window,
  error-budget burn rate against a configurable objective, and
  goodput-vs-throughput token counters. It runs on an injectable monotonic
  clock (``runtime/clock.py`` protocol: any ``() -> float``), so the fleet
  simulator feeds the *production* accountant on its virtual clock and the
  sim's SLA invariants are derived from the same code the frontend serves
  on ``/debug/slo``. All accounting is host-side arithmetic on timestamps
  the serving path already takes — zero new device syncs.

Exported metrics (through ``runtime/metrics.py`` scopes):
``dtpu_slo_attainment_ratio{model,sla_class,window,slo}``,
``dtpu_slo_burn_rate{model,sla_class,window}``,
``dtpu_goodput_tokens_total{model,sla_class}``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from .config import (
    ENV_SLA_CLASSES,
    ENV_SLA_DEFAULT,
    ENV_SLO_OBJECTIVE,
    env_float,
    env_str,
)
from .logging import get_logger

log = get_logger("slo")

# annotation key on PreprocessedRequest.annotations (rides the request plane
# like "traceparent"); HTTP header the frontend accepts the class from
ANNOTATION_SLA = "sla"
SLA_HEADER = "x-dtpu-sla"

# the rolling windows every consumer reads, plus the cumulative ledger
WINDOWS: Dict[str, float] = {"1m": 60.0, "5m": 300.0, "1h": 3600.0}
TOTAL_WINDOW = "total"
_BUCKET_S = 10.0  # rolling-window resolution
_RETAIN_S = max(WINDOWS.values())

DEFAULT_OBJECTIVE = 0.99
DEFAULT_CLASS = "standard"


@dataclasses.dataclass(frozen=True)
class SlaSpec:
    """One request's promise: class name + latency targets (+ e2e deadline).

    ``deadline_s`` is a *relative* budget from frontend receipt (0 = none);
    the absolute anchor travels separately as ``t0_ns`` in the annotation so
    downstream hops on the same wall clock can compute remaining budget.
    """

    sla_class: str
    ttft_target_s: float
    itl_target_s: float
    deadline_s: float = 0.0

    def to_annotation(self, t0_ns: Optional[int] = None) -> Dict[str, Any]:
        ann: Dict[str, Any] = {
            "class": self.sla_class,
            "ttft_target_s": self.ttft_target_s,
            "itl_target_s": self.itl_target_s,
            "deadline_s": self.deadline_s,
        }
        ann["t0_ns"] = int(t0_ns) if t0_ns is not None else time.time_ns()
        return ann


def spec_from_annotations(annotations: Dict[str, Any]) -> Optional[SlaSpec]:
    """Parse the ``sla`` annotation back into a spec (None when absent or
    malformed — a bad annotation must degrade to unclassified, not 500)."""
    ann = (annotations or {}).get(ANNOTATION_SLA)
    if not isinstance(ann, dict) or "class" not in ann:
        return None
    try:
        return SlaSpec(
            sla_class=str(ann["class"]),
            ttft_target_s=float(ann.get("ttft_target_s", 0.0)),
            itl_target_s=float(ann.get("itl_target_s", 0.0)),
            deadline_s=float(ann.get("deadline_s", 0.0)),
        )
    except (TypeError, ValueError):
        return None


def sla_t0_ns(annotations: Dict[str, Any]) -> Optional[int]:
    """Frontend receipt stamp (unix ns) riding the sla annotation."""
    ann = (annotations or {}).get(ANNOTATION_SLA)
    if isinstance(ann, dict):
        try:
            return int(ann["t0_ns"])
        except (KeyError, TypeError, ValueError):
            return None
    return None


# ---------------------------------------------------------------------------
# class registry: built-in defaults < env < per-model card overrides
# ---------------------------------------------------------------------------

_BUILTIN_CLASSES: Dict[str, SlaSpec] = {
    "interactive": SlaSpec("interactive", ttft_target_s=0.5, itl_target_s=0.05),
    "standard": SlaSpec("standard", ttft_target_s=2.0, itl_target_s=0.2),
    "batch": SlaSpec("batch", ttft_target_s=30.0, itl_target_s=1.0),
}


def _parse_class_spec(name: str, body: str) -> SlaSpec:
    """``ttft=0.5,itl=0.05,deadline=30`` -> SlaSpec (keys optional; unset
    targets inherit the built-in class of the same name when one exists)."""
    base = _BUILTIN_CLASSES.get(name, SlaSpec(name, 0.0, 0.0))
    fields = {
        "ttft": base.ttft_target_s,
        "itl": base.itl_target_s,
        "deadline": base.deadline_s,
    }
    for part in body.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        k = k.strip()
        if k not in fields:
            raise ValueError(f"unknown SLA target {k!r} (want ttft/itl/deadline)")
        fields[k] = float(v)
    return SlaSpec(name, fields["ttft"], fields["itl"], fields["deadline"])


def sla_classes() -> Dict[str, SlaSpec]:
    """The effective named-class table: built-ins overlaid with
    ``DTPU_SLA_CLASSES`` ("name:ttft=0.5,itl=0.05;name2:ttft=30"). A
    malformed env spec logs and falls back to built-ins — SLA config must
    never take the frontend down."""
    out = dict(_BUILTIN_CLASSES)
    raw = env_str(ENV_SLA_CLASSES, "")
    if not raw:
        return out
    try:
        for entry in raw.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            name, _, body = entry.partition(":")
            name = name.strip()
            if not name:
                raise ValueError(f"class entry {entry!r} has no name")
            out[name] = _parse_class_spec(name, body)
    except ValueError:
        log.exception("bad %s spec %r; using built-in SLA classes",
                      ENV_SLA_CLASSES, raw)
        return dict(_BUILTIN_CLASSES)
    return out


def default_class() -> str:
    return env_str(ENV_SLA_DEFAULT, DEFAULT_CLASS)


def resolve_sla(
    name: Optional[str],
    model_overrides: Optional[Dict[str, Dict[str, Any]]] = None,
) -> Optional[SlaSpec]:
    """Resolve a class name to its spec, applying per-model target
    overrides from the model card's ``runtime_config.sla_classes``
    (``{"interactive": {"ttft_target_s": 0.3}}``). ``None``/empty name
    means the default class; an unknown name returns None (the frontend
    turns that into a 400 rather than silently serving untracked)."""
    explicit = bool(name)
    name = name or default_class()
    spec = sla_classes().get(name)
    if spec is None and not explicit:
        # a typo'd DTPU_SLA_DEFAULT must not 400 every unclassed request
        # (same never-take-the-frontend-down rule as the class table):
        # fall back to the built-in default, loudly
        log.warning("%s names unknown class %r; using %r",
                    ENV_SLA_DEFAULT, name, DEFAULT_CLASS)
        name = DEFAULT_CLASS
        spec = sla_classes().get(name)
    ov = (model_overrides or {}).get(name)
    if ov:
        base = spec or SlaSpec(name, 0.0, 0.0)
        try:
            spec = SlaSpec(
                name,
                float(ov.get("ttft_target_s", base.ttft_target_s)),
                float(ov.get("itl_target_s", base.itl_target_s)),
                float(ov.get("deadline_s", base.deadline_s)),
            )
        except (TypeError, ValueError):
            log.warning("bad sla_classes override for %r on model card; "
                        "ignoring", name)
    return spec


# ---------------------------------------------------------------------------
# attainment math (the one implementation: loadgen, profiler, sim, frontend)
# ---------------------------------------------------------------------------


def attainment(values: Iterable[float], target: float) -> float:
    """Fraction of ``values`` at or under ``target`` (0.0 for no samples —
    matches the historical loadgen convention so replay JSON is stable)."""
    vals = list(values)
    if not vals:
        return 0.0
    return sum(1 for v in vals if v <= target) / len(vals)


def burn_rate(att: Optional[float], objective: float) -> Optional[float]:
    """Error-budget burn rate: observed error rate over the budgeted error
    rate. 1.0 = spending budget exactly on schedule; >1 = burning faster
    than the objective allows; None when there is nothing observed."""
    if att is None:
        return None
    allowed = max(1.0 - objective, 1e-9)
    return (1.0 - att) / allowed


# ---------------------------------------------------------------------------
# the accountant
# ---------------------------------------------------------------------------


class _Counts:
    """One accumulation cell (a time bucket or a cumulative total)."""

    __slots__ = ("ttft_ok", "ttft_n", "itl_ok", "itl_n", "met", "requests",
                 "goodput_tokens", "tokens")

    def __init__(self) -> None:
        self.ttft_ok = 0
        self.ttft_n = 0
        self.itl_ok = 0
        self.itl_n = 0
        self.met = 0
        self.requests = 0
        self.goodput_tokens = 0
        self.tokens = 0

    def add(self, other: "_Counts") -> None:
        self.ttft_ok += other.ttft_ok
        self.ttft_n += other.ttft_n
        self.itl_ok += other.itl_ok
        self.itl_n += other.itl_n
        self.met += other.met
        self.requests += other.requests
        self.goodput_tokens += other.goodput_tokens
        self.tokens += other.tokens


class _Series:
    """Per-(model, sla_class) state: bucket ring + cumulative totals."""

    __slots__ = ("buckets", "total", "spec")

    def __init__(self, spec: SlaSpec) -> None:
        self.buckets: Dict[int, _Counts] = {}
        self.total = _Counts()
        self.spec = spec


class SloAccountant:
    """Rolling per-(model, sla_class) SLO ledger on an injectable clock.

    ``clock`` is any monotonic ``() -> float`` (``runtime/clock.py``'s
    ``Clock.time`` or a virtual clock's). Observations are compared against
    the *per-request* spec (targets may differ per model override), so the
    ledger is correct even when one class means different numbers on
    different models. Thread-safe: the engine feeds it from executor
    threads, the status server reads it from the event loop.
    """

    def __init__(
        self,
        clock: Optional[Callable[[], float]] = None,
        objective: Optional[float] = None,
        metrics=None,
    ):
        self._clock = clock if clock is not None else time.monotonic
        self.objective = (
            objective if objective is not None
            else env_float(ENV_SLO_OBJECTIVE, DEFAULT_OBJECTIVE)
        )
        self._lock = threading.Lock()
        self._series: Dict[tuple, _Series] = {}
        self._metrics = None
        self._goodput_c = None
        self._attain_g = None
        self._burn_g = None
        if metrics is not None:
            self.bind_metrics(metrics)

    # -- wiring ---------------------------------------------------------------
    def bind_metrics(self, scope) -> None:
        """Attach a MetricsScope: the goodput counter increments on every
        record; attainment/burn gauges refresh on export_metrics()."""
        from . import metrics as M

        self._metrics = scope
        self._goodput_c = scope.counter(
            M.GOODPUT_TOKENS, "output tokens of requests that met their SLO",
            extra_labels=(M.LABEL_MODEL, M.LABEL_SLA_CLASS),
        )
        self._attain_g = scope.gauge(
            M.SLO_ATTAINMENT, "fraction of requests meeting the SLO",
            extra_labels=(M.LABEL_MODEL, M.LABEL_SLA_CLASS, M.LABEL_WINDOW,
                          "slo"),
        )
        self._burn_g = scope.gauge(
            M.SLO_BURN_RATE, "error-budget burn rate (1.0 = on schedule)",
            extra_labels=(M.LABEL_MODEL, M.LABEL_SLA_CLASS, M.LABEL_WINDOW),
        )

    # -- producer side --------------------------------------------------------
    def record(
        self,
        model: str,
        spec: SlaSpec,
        ttft_s: Optional[float] = None,
        itl_s: Optional[float] = None,
        output_tokens: int = 0,
        e2e_s: Optional[float] = None,
    ) -> bool:
        """Account one finished request; returns whether it met its SLO.

        ``itl_s`` is the request's mean inter-token gap (None when fewer
        than two tokens streamed — an unobserved ITL cannot violate).
        """
        now = self._clock()
        ttft_ok = ttft_s is not None and ttft_s <= spec.ttft_target_s
        itl_ok = itl_s is None or itl_s <= spec.itl_target_s
        deadline_ok = (
            spec.deadline_s <= 0.0
            or (e2e_s is not None and e2e_s <= spec.deadline_s)
        )
        met = ttft_ok and itl_ok and deadline_ok
        key = (model, spec.sla_class)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _Series(spec)
            series.spec = spec  # latest targets win for the payload
            bidx = int(now / _BUCKET_S)
            bucket = series.buckets.get(bidx)
            if bucket is None:
                bucket = series.buckets[bidx] = _Counts()
                self._prune(series, now)
            for cell in (bucket, series.total):
                cell.requests += 1
                if ttft_s is not None:
                    cell.ttft_n += 1
                    cell.ttft_ok += 1 if ttft_ok else 0
                if itl_s is not None:
                    cell.itl_n += 1
                    cell.itl_ok += 1 if itl_s <= spec.itl_target_s else 0
                cell.met += 1 if met else 0
                cell.tokens += int(output_tokens)
                if met:
                    cell.goodput_tokens += int(output_tokens)
        if met and output_tokens and self._goodput_c is not None:
            self._goodput_c.inc(
                int(output_tokens), model=model, sla_class=spec.sla_class
            )
        return met

    @staticmethod
    def _prune(series: _Series, now: float) -> None:
        floor = int((now - _RETAIN_S) / _BUCKET_S) - 1
        for bidx in [b for b in series.buckets if b < floor]:
            del series.buckets[bidx]

    # -- consumer side --------------------------------------------------------
    def _window_counts(self, series: _Series, window: str, now: float) -> _Counts:
        if window == TOTAL_WINDOW:
            return series.total
        span = WINDOWS[window]
        floor = int((now - span) / _BUCKET_S) + 1  # whole buckets inside span
        agg = _Counts()
        for bidx, bucket in series.buckets.items():
            if bidx >= floor:
                agg.add(bucket)
        return agg

    def attainment(
        self,
        model: str,
        sla_class: str,
        window: str = TOTAL_WINDOW,
        kind: str = "combined",
    ) -> Optional[float]:
        """Attainment ratio over ``window`` — ``kind`` picks the objective:
        ``ttft`` / ``itl`` / ``combined`` (ttft AND itl AND deadline).
        None when nothing was observed in the window."""
        with self._lock:
            series = self._series.get((model, sla_class))
            if series is None:
                return None
            c = self._window_counts(series, window, self._clock())
        if kind == "ttft":
            return c.ttft_ok / c.ttft_n if c.ttft_n else None
        if kind == "itl":
            return c.itl_ok / c.itl_n if c.itl_n else None
        return c.met / c.requests if c.requests else None

    def burn_rate(
        self, model: str, sla_class: str, window: str = TOTAL_WINDOW
    ) -> Optional[float]:
        return burn_rate(
            self.attainment(model, sla_class, window), self.objective
        )

    def keys(self) -> List[tuple]:
        with self._lock:
            return sorted(self._series)

    def snapshot(self) -> Dict[str, Any]:
        """The ``/debug/slo`` payload: every (model, class) series with all
        windows, targets, burn rates and goodput counters. Values rounded
        so the sim's byte-identity pins hold."""
        now = self._clock()
        out: Dict[str, Any] = {
            "objective": self.objective,
            "windows": sorted(WINDOWS) + [TOTAL_WINDOW],
            "models": {},
        }

        def _r(x: Optional[float]) -> Optional[float]:
            return None if x is None else round(x, 6)

        with self._lock:
            items = [
                (key, series, {
                    w: self._window_counts(series, w, now)
                    for w in list(WINDOWS) + [TOTAL_WINDOW]
                })
                for key, series in sorted(self._series.items())
            ]
        for (model, cls), series, per_window in items:
            spec = series.spec
            windows_obj = {}
            for w, c in per_window.items():
                att_t = c.ttft_ok / c.ttft_n if c.ttft_n else None
                att_i = c.itl_ok / c.itl_n if c.itl_n else None
                att_c = c.met / c.requests if c.requests else None
                windows_obj[w] = {
                    "requests": c.requests,
                    "ttft_attainment": _r(att_t),
                    "itl_attainment": _r(att_i),
                    "attainment": _r(att_c),
                    "burn_rate": _r(burn_rate(att_c, self.objective)),
                    "goodput_tokens": c.goodput_tokens,
                    "total_tokens": c.tokens,
                    "goodput_ratio": _r(
                        c.goodput_tokens / c.tokens if c.tokens else None
                    ),
                }
            out["models"].setdefault(model, {})[cls] = {
                "targets": {
                    "ttft_target_s": spec.ttft_target_s,
                    "itl_target_s": spec.itl_target_s,
                    "deadline_s": spec.deadline_s,
                },
                "windows": windows_obj,
            }
        return out

    def export_metrics(self) -> None:
        """Refresh the attainment/burn gauges from the rolling windows
        (called right before a scrape / debug read; no-op when unbound).

        An empty window writes the neutral values (attainment 1.0, burn
        0.0) instead of skipping: skipping would freeze a drained 1m/5m
        gauge at its last value — a one-minute violation burst would keep
        exporting page-now burn rates for hours after traffic stopped.
        No traffic burns no error budget; request counts live in the
        ``/debug/slo`` payload for consumers that need to tell idle from
        perfect."""
        if self._attain_g is None:
            return
        for model, cls in self.keys():
            for w in list(WINDOWS) + [TOTAL_WINDOW]:
                for kind in ("ttft", "itl", "combined"):
                    att = self.attainment(model, cls, w, kind)
                    self._attain_g.set(
                        att if att is not None else 1.0,
                        model=model, sla_class=cls, window=w, slo=kind,
                    )
                br = self.burn_rate(model, cls, w)
                self._burn_g.set(
                    br if br is not None else 0.0,
                    model=model, sla_class=cls, window=w,
                )


# ---------------------------------------------------------------------------
# flight-recorder integration: the /debug/requests?id= budget breakdown
# ---------------------------------------------------------------------------


def budget_breakdown(flight: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """From one flight-recorder timeline, the SLO view of a request: where
    the TTFT budget went (queue / prefill / decode shares of the target)
    and the remaining e2e deadline. Needs the engine-stamped ``queued``
    event to carry the sla fields; returns None for unclassified flights."""
    events = flight.get("events") or []

    def _find(kind: str):
        for e in events:
            if e["event"].get("kind") == kind:
                return e
        return None

    queued = _find("queued")
    if queued is None:
        return None
    ev = queued["event"]
    if "sla_class" not in ev:
        return None
    ttft_target_s = float(ev.get("ttft_target_s", 0.0))
    deadline_s = float(ev.get("deadline_s", 0.0))
    t_queued = queued["timestamp"]
    admitted = _find("admitted")
    first = _find("first_token")
    terminal = _find("finish") or _find("abort")
    out: Dict[str, Any] = {
        "sla_class": ev["sla_class"],
        "ttft_target_s": ttft_target_s,
        "deadline_s": deadline_s,
    }

    def _ms(a, b) -> float:
        return round((b["timestamp"] - a["timestamp"]) / 1e6, 3)

    phases: Dict[str, float] = {}
    if admitted is not None:
        phases["queue_ms"] = _ms(queued, admitted)
        if first is not None:
            phases["prefill_ms"] = _ms(admitted, first)
    if first is not None:
        phases["ttft_ms"] = _ms(queued, first)
        if terminal is not None:
            phases["decode_ms"] = _ms(first, terminal)
    out.update(phases)
    if ttft_target_s > 0:
        target_ms = ttft_target_s * 1e3
        out["budget_shares"] = {
            name[:-3]: round(phases[name] / target_ms, 4)
            for name in ("queue_ms", "prefill_ms")
            if name in phases
        }
        if "ttft_ms" in phases:
            out["ttft_met"] = phases["ttft_ms"] <= target_ms
    if deadline_s > 0 and terminal is not None:
        out["deadline_remaining_s"] = round(
            deadline_s - (terminal["timestamp"] - t_queued) / 1e9, 3
        )
    return out


# ---------------------------------------------------------------------------
# /debug/slo payload + bench detail (shared by StatusServer, frontend, bench)
# ---------------------------------------------------------------------------


def debug_slo_payload(accountant: Optional["SloAccountant"]) -> Dict[str, Any]:
    """The ONE ``/debug/slo`` body both the worker StatusServer and the HTTP
    frontend serve."""
    if accountant is None:
        return {"objective": None, "windows": [], "models": {}}
    accountant.export_metrics()
    return accountant.snapshot()


def bench_slo_detail(
    samples: List[tuple],
    model: str = "bench",
    objective: float = DEFAULT_OBJECTIVE,
) -> Dict[str, Any]:
    """The BENCH JSON ``detail.slo`` record: what attainment + burn rate the
    measured latencies would score against every named class's targets.
    ``samples`` is ``[(ttft_s, itl_mean_s_or_None, output_tokens), ...]``;
    deterministic given the samples (fixed clock, total window only)."""
    t = [0.0]
    acct = SloAccountant(clock=lambda: t[0], objective=objective)
    for name, spec in sorted(sla_classes().items()):
        for ttft_s, itl_s, tokens in samples:
            # e2e approximated from the sample itself so classes with a
            # deadline= target score against it instead of auto-missing
            e2e_s = ttft_s + (itl_s or 0.0) * max(int(tokens) - 1, 0)
            acct.record(model, spec, ttft_s=ttft_s, itl_s=itl_s,
                        output_tokens=int(tokens), e2e_s=e2e_s)
    snap = acct.snapshot()
    classes = {}
    for name, body in snap["models"].get(model, {}).items():
        tw = body["windows"][TOTAL_WINDOW]
        classes[name] = {
            "ttft_target_s": body["targets"]["ttft_target_s"],
            "itl_target_s": body["targets"]["itl_target_s"],
            "ttft_attainment": tw["ttft_attainment"],
            "itl_attainment": tw["itl_attainment"],
            "attainment": tw["attainment"],
            "burn_rate": tw["burn_rate"],
            "goodput_tokens": tw["goodput_tokens"],
            "total_tokens": tw["total_tokens"],
        }
    return {"objective": objective, "requests": len(samples),
            "classes": classes}


# ---------------------------------------------------------------------------
# process-global accountant (the engine/worker-side ledger, like the flight
# recorder: importable anywhere without wiring)
# ---------------------------------------------------------------------------

_global_accountant: Optional[SloAccountant] = None
_global_lock = threading.Lock()


def get_slo_accountant() -> SloAccountant:
    global _global_accountant
    if _global_accountant is None:
        with _global_lock:
            if _global_accountant is None:
                _global_accountant = SloAccountant()
    return _global_accountant


def set_slo_accountant(accountant: Optional[SloAccountant]) -> None:
    global _global_accountant
    _global_accountant = accountant
