"""Critical-path attribution: where did a request's end-to-end time go?

PR 11's SLO accountant (runtime/slo.py) says *whether* a class is missing
its promise; this module says *why*. Every finished request already leaves
a flight-recorder timeline of milestone events (received, tokenized,
routed, fetch/transfer, queued, admitted, first_token, finish) — the
recorder stamps them, nobody adds timestamps for us. :func:`attribute`
decomposes that timeline into an exhaustive, non-overlapping phase
breakdown that **provably sums to the e2e duration**: the gap between each
consecutive pair of events is charged to exactly one phase (keyed on the
later event's kind, with a lifecycle-position fallback for kinds the table
does not know), and all arithmetic is integer nanoseconds, so

    sum(phases) == last_event_ts - first_event_ts        (exactly)

holds for ANY timeline, including ones with unknown or out-of-order kinds.

Phases (the fixed schema every consumer reads):

- ``frontend_queue``  — HTTP receipt -> tokenized (parse + tokenize)
- ``route``           — routing decisions, dispatch, request-plane hops
- ``kv_fetch``        — peer-tier/disagg KV fetch + tier onboarding
- ``prefill_queue``   — engine admission wait (queued -> admitted)
- ``prefill_compute`` — admitted -> first token
- ``decode``          — first token -> terminal finish/abort
- ``epilogue``        — anything after the terminal event (frontend flush,
  accounting) in merged frontend+worker timelines

Three consumers, one decomposition:

- ``/debug/requests?id=`` gains an ``attribution`` section next to the
  ``slo`` budget breakdown (runtime/flight_recorder.py grafts it);
- ``dtpu_request_phase_seconds{phase,sla_class}`` histograms;
- :class:`AttributionAggregator` keeps rolling per-(model, class)
  "where does p99 go" dominant-phase aggregates on the same
  clock-injectable windowed machinery as the SLO accountant, so the fleet
  simulator drives the production code on its virtual clock and
  ``/debug/fleet`` merges the same snapshots the planner reads.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .logging import get_logger

log = get_logger("attribution")

# the fixed phase schema, in lifecycle order
PHASES: Tuple[str, ...] = (
    "frontend_queue",
    "route",
    "kv_fetch",
    "prefill_queue",
    "prefill_compute",
    "decode",
    "epilogue",
)

# event kind -> phase charged for the gap ENDING at this event. Kinds not
# listed fall back to the lifecycle position (see _fallback_phase): the
# decomposition must stay exhaustive when new kinds appear.
_PHASE_OF_GAP_END: Dict[str, Optional[str]] = {
    "received": None,               # timeline origin
    "tokenized": "frontend_queue",
    "routed": "route",
    "prefill_routed": "route",
    "prefill_streamed": "route",
    "prefill_deflected": "route",
    "global_kv_plan": "route",
    "fetch_started": "route",       # dispatch up to the moment the fetch began
    "fetch_committed": "kv_fetch",
    "fetch_aborted": "kv_fetch",
    "transfer": "kv_fetch",
    "onboard": "kv_fetch",
    "queued": "route",
    "admitted": "prefill_queue",
    "first_token": "prefill_compute",
    "migration": "decode",
    "slo_violation": "decode",
    "finish": "decode",
    "abort": "decode",
}

_TERMINAL_KINDS = ("finish", "abort")


def _fallback_phase(seen: Dict[str, bool]) -> str:
    """Phase for an unknown kind, from the milestones already passed."""
    if seen.get("terminal"):
        return "epilogue"
    if seen.get("first_token"):
        return "decode"
    if seen.get("admitted"):
        return "prefill_compute"
    if seen.get("queued"):
        return "prefill_queue"
    if seen.get("tokenized"):
        return "route"
    return "frontend_queue"


def attribute(flight: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Decompose one flight-recorder timeline into the phase breakdown.

    ``flight`` is the recorder's timeline dict (``events`` is a list of
    ``{"timestamp": unix_ns, "event": {"kind": ...}}``). Returns None for
    timelines with fewer than two events (no duration to attribute).
    All sums are integer ns: ``sum(phases_ns.values()) == e2e_ns`` exactly.
    """
    events = flight.get("events") or []
    if len(events) < 2:
        return None
    ordered = sorted(events, key=lambda e: e["timestamp"])
    phases_ns: Dict[str, int] = {p: 0 for p in PHASES}
    seen: Dict[str, bool] = {}
    prev_ts = ordered[0]["timestamp"]
    _note(seen, ordered[0]["event"].get("kind"))
    for entry in ordered[1:]:
        ts = entry["timestamp"]
        kind = entry["event"].get("kind")
        gap = max(int(ts) - int(prev_ts), 0)
        if seen.get("terminal"):
            phase = "epilogue"
        else:
            phase = _PHASE_OF_GAP_END.get(kind) or _fallback_phase(seen)
        phases_ns[phase] += gap
        _note(seen, kind)
        prev_ts = ts
    e2e_ns = int(ordered[-1]["timestamp"]) - int(ordered[0]["timestamp"])
    dominant = max(PHASES, key=lambda p: (phases_ns[p], -PHASES.index(p)))
    return {
        "e2e_ns": e2e_ns,
        "phases_ns": phases_ns,
        "dominant": dominant,
        "events": len(ordered),
    }


def _note(seen: Dict[str, bool], kind: Optional[str]) -> None:
    if kind in _TERMINAL_KINDS:
        seen["terminal"] = True
    elif kind in ("tokenized", "queued", "admitted", "first_token"):
        seen[kind] = True


def attribution_breakdown(flight: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The ``/debug/requests?id=`` ``attribution`` section: phase seconds +
    shares, human-readable, derived from :func:`attribute`."""
    attr = attribute(flight)
    if attr is None:
        return None
    e2e_ns = attr["e2e_ns"]
    out: Dict[str, Any] = {
        "e2e_s": round(e2e_ns / 1e9, 6),
        "dominant": attr["dominant"],
        "phases": {
            p: round(ns / 1e9, 6) for p, ns in attr["phases_ns"].items()
        },
    }
    if e2e_ns > 0:
        out["shares"] = {
            p: round(ns / e2e_ns, 4) for p, ns in attr["phases_ns"].items()
        }
    return out


# ---------------------------------------------------------------------------
# rolling per-(model, class) aggregates — the "where does p99 go" ledger
# ---------------------------------------------------------------------------

# same windowing constants as the SLO accountant (runtime/slo.py): the two
# ledgers answer "is the promise kept" / "where does the time go" over the
# same horizons
WINDOWS: Dict[str, float] = {"1m": 60.0, "5m": 300.0, "1h": 3600.0}
TOTAL_WINDOW = "total"
_BUCKET_S = 10.0
_RETAIN_S = max(WINDOWS.values())
# per-bucket sample cap: p99 needs the tail samples, not all of them; a
# 10s bucket holding 512 requests bounds memory at fleet rates while the
# count/sum aggregates stay exact
_BUCKET_SAMPLES = 512

_HIST_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                 2.5, 5.0, 15.0, 60.0)


class _Bucket:
    __slots__ = ("count", "e2e_sum_ns", "phase_sums_ns", "samples",
                 "dropped")

    def __init__(self) -> None:
        self.count = 0
        self.e2e_sum_ns = 0
        self.phase_sums_ns = {p: 0 for p in PHASES}
        # (e2e_ns, phases_ns) pairs for tail percentiles
        self.samples: List[Tuple[int, Dict[str, int]]] = []
        self.dropped = 0


class AttributionAggregator:
    """Rolling per-(model, sla_class) phase aggregates on an injectable
    clock — the exact windowed-bucket machinery of ``SloAccountant``.
    Thread-safe: the engine feeds it from executor threads, the status
    servers read it from the event loop."""

    def __init__(
        self,
        clock: Optional[Callable[[], float]] = None,
        metrics=None,
    ):
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        # (model, sla_class) -> {bidx: _Bucket}, plus a cumulative bucket
        self._buckets: Dict[tuple, Dict[int, _Bucket]] = {}
        self._totals: Dict[tuple, _Bucket] = {}
        self._phase_h = None
        if metrics is not None:
            self.bind_metrics(metrics)

    def bind_metrics(self, scope) -> None:
        from . import metrics as M

        self._phase_h = scope.histogram(
            M.REQUEST_PHASE_SECONDS,
            "per-request critical-path phase duration",
            extra_labels=(M.LABEL_MODEL, M.LABEL_SLA_CLASS, "phase"),
            buckets=_HIST_BUCKETS,
        )

    # -- producer side -------------------------------------------------------
    def observe(
        self,
        model: str,
        sla_class: str,
        attr: Dict[str, Any],
    ) -> None:
        """Fold one :func:`attribute` result into the rolling windows (and
        the phase histograms when metrics are bound)."""
        e2e_ns = int(attr["e2e_ns"])
        phases_ns = attr["phases_ns"]
        key = (model, sla_class)
        now = self._clock()
        with self._lock:
            per = self._buckets.setdefault(key, {})
            total = self._totals.setdefault(key, _Bucket())
            bidx = int(now / _BUCKET_S)
            bucket = per.get(bidx)
            if bucket is None:
                bucket = per[bidx] = _Bucket()
                floor = int((now - _RETAIN_S) / _BUCKET_S) - 1
                for old in [b for b in per if b < floor]:
                    del per[old]
            for cell in (bucket, total):
                cell.count += 1
                cell.e2e_sum_ns += e2e_ns
                for p in PHASES:
                    cell.phase_sums_ns[p] += int(phases_ns.get(p, 0))
                if len(cell.samples) < _BUCKET_SAMPLES:
                    cell.samples.append((e2e_ns, dict(phases_ns)))
                else:
                    cell.dropped += 1
        if self._phase_h is not None:
            for p in PHASES:
                ns = int(phases_ns.get(p, 0))
                if ns > 0:
                    self._phase_h.observe(
                        ns / 1e9, model=model, sla_class=sla_class, phase=p
                    )

    def observe_flight(
        self, model: str, sla_class: str, flight: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        """Convenience: attribute a timeline and fold it in; returns the
        attribution (None when the timeline was too short to decompose)."""
        attr = attribute(flight)
        if attr is not None:
            self.observe(model, sla_class, attr)
        return attr

    # -- consumer side -------------------------------------------------------
    def _window_cells(self, key: tuple, window: str, now: float) -> List[_Bucket]:
        if window == TOTAL_WINDOW:
            total = self._totals.get(key)
            return [total] if total is not None else []
        span = WINDOWS[window]
        floor = int((now - span) / _BUCKET_S) + 1
        per = self._buckets.get(key, {})
        return [b for bidx, b in per.items() if bidx >= floor]

    def snapshot(self) -> Dict[str, Any]:
        """The ``/debug``-facing payload: per (model, class) per window,
        mean phase shares, the dominant phase of the p99 tail, and the tail
        e2e. Values rounded so the sim's byte-identity pins hold."""
        now = self._clock()
        out: Dict[str, Any] = {
            "windows": sorted(WINDOWS) + [TOTAL_WINDOW],
            "phases": list(PHASES),
            "models": {},
        }
        with self._lock:
            keys = sorted(set(self._buckets) | set(self._totals))
            gathered = {
                key: {
                    w: [
                        (c.count, c.e2e_sum_ns, dict(c.phase_sums_ns),
                         list(c.samples), c.dropped)
                        for c in self._window_cells(key, w, now)
                    ]
                    for w in list(WINDOWS) + [TOTAL_WINDOW]
                }
                for key in keys
            }
        for (model, cls), per_window in gathered.items():
            windows_obj = {}
            for w, cells in per_window.items():
                count = sum(c[0] for c in cells)
                if count == 0:
                    windows_obj[w] = {"requests": 0}
                    continue
                e2e_sum = sum(c[1] for c in cells)
                phase_sums = {p: sum(c[2][p] for c in cells) for p in PHASES}
                samples: List[Tuple[int, Dict[str, int]]] = []
                for c in cells:
                    samples.extend(c[3])
                dropped = sum(c[4] for c in cells)
                windows_obj[w] = _window_body(
                    count, e2e_sum, phase_sums, samples, dropped
                )
            out["models"].setdefault(model, {})[cls] = windows_obj
        return out


def _window_body(
    count: int,
    e2e_sum_ns: int,
    phase_sums_ns: Dict[str, int],
    samples: List[Tuple[int, Dict[str, int]]],
    dropped: int,
) -> Dict[str, Any]:
    body: Dict[str, Any] = {
        "requests": count,
        "e2e_mean_s": round(e2e_sum_ns / count / 1e9, 6),
        "mean_share": {
            p: round(ns / e2e_sum_ns, 4) if e2e_sum_ns else 0.0
            for p, ns in phase_sums_ns.items()
        },
    }
    body["dominant"] = max(
        PHASES, key=lambda p: (phase_sums_ns[p], -PHASES.index(p))
    )
    if samples:
        tail = tail_samples(samples)
        tail_e2e = sum(s[0] for s in tail)
        tail_phases = {
            p: sum(int(s[1].get(p, 0)) for s in tail) for p in PHASES
        }
        body["p99"] = {
            "e2e_s": round(min(s[0] for s in tail) / 1e9, 6),
            "dominant": max(
                PHASES, key=lambda p: (tail_phases[p], -PHASES.index(p))
            ),
            "share": {
                p: round(ns / tail_e2e, 4) if tail_e2e else 0.0
                for p, ns in tail_phases.items()
            },
        }
    if dropped:
        body["sampled_out"] = dropped
    return body


def tail_samples(
    samples: List[Tuple[int, Dict[str, int]]], q: float = 0.99
) -> List[Tuple[int, Dict[str, int]]]:
    """The slowest ``ceil((1-q) * n)`` samples by e2e — the requests at and
    beyond the q-th percentile, whose phase sums define "where p99 goes"."""
    n = len(samples)
    k = max(1, n - int(q * n))
    return sorted(samples, key=lambda s: s[0])[-k:]


# ---------------------------------------------------------------------------
# bench detail (bench.py detail.attribution; schema pinned in tier-1)
# ---------------------------------------------------------------------------


def bench_attribution_detail(
    breakdowns: List[Dict[str, int]],
) -> Dict[str, Any]:
    """The BENCH JSON ``detail.attribution`` record from the timed (post-
    warmup) requests' phase breakdowns. ``breakdowns`` is a list of
    ``phases_ns`` dicts (one per request, :func:`attribute` output).
    Deterministic given the inputs."""
    phases = {p: [int(b.get(p, 0)) for b in breakdowns] for p in PHASES}
    e2es = [sum(b.get(p, 0) for p in PHASES) for b in breakdowns]
    n = len(breakdowns)
    out: Dict[str, Any] = {
        "requests": n,
        "phases": {},
        "e2e_mean_s": round(sum(e2es) / n / 1e9, 6) if n else 0.0,
        "dominant": None,
    }
    if not n:
        return out
    e2e_total = sum(e2es)

    def _p99(vals: List[int]) -> float:
        s = sorted(vals)
        return s[min(len(s) - 1, int(0.99 * len(s)))] / 1e9

    for p in PHASES:
        vals = phases[p]
        total = sum(vals)
        out["phases"][p] = {
            "mean_s": round(total / n / 1e9, 6),
            "p99_s": round(_p99(vals), 6),
            "mean_share": round(total / e2e_total, 4) if e2e_total else 0.0,
        }
    out["dominant"] = max(
        PHASES, key=lambda p: (sum(phases[p]), -PHASES.index(p))
    )
    return out


# ---------------------------------------------------------------------------
# process-global aggregator (like the flight recorder / SLO accountant:
# importable anywhere without wiring)
# ---------------------------------------------------------------------------

_global_aggregator: Optional[AttributionAggregator] = None
_global_lock = threading.Lock()


def get_attribution() -> AttributionAggregator:
    global _global_aggregator
    if _global_aggregator is None:
        with _global_lock:
            if _global_aggregator is None:
                _global_aggregator = AttributionAggregator()
    return _global_aggregator


def set_attribution(agg: Optional[AttributionAggregator]) -> None:
    global _global_aggregator
    _global_aggregator = agg
