"""Unified resilience policy: retry with backoff + circuit breaker.

One shared implementation for every communication plane (request plane,
event plane, discovery, KV transfer, deploy controller, planner connectors)
instead of the scattered ad-hoc backoff loops each of them used to carry.
Reference analogs: the NATS client's reconnect policy and the operator's
restart backoff (deploy/operator/internal/controller/) — here unified into
two primitives:

- ``RetryPolicy``: bounded attempts, exponential backoff with decorrelated
  jitter (sleep_n = min(cap, U(base, 3 * sleep_{n-1}))), optional per-attempt
  timeout and total deadline, and a retryable-error predicate so terminal
  errors (typed 4xx-class failures) are never retried.
- ``CircuitBreaker``: closed/open/half-open with a sliding failure-rate
  window. Open circuits fail fast with ``CircuitOpenError`` (callers map it
  to busy-503 + Retry-After); after ``reset_timeout_s`` a bounded number of
  half-open probes decides reopen vs close.

Both work sync and async, are configured through the ``DTPU_*`` catalog
(``DTPU_RETRY_DEFAULT`` / ``DTPU_RETRY_<SCOPE>``, ``DTPU_CB_DEFAULT`` /
``DTPU_CB_<SCOPE>`` — compact ``key=value,key=value`` specs, runtime/config.py),
and export per-policy Prometheus counters through runtime/metrics.py.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Optional, Tuple, Type

from . import metrics as M
from .errors import is_terminal
from .logging import get_logger

log = get_logger("runtime.resilience")

# env spec prefixes (catalogued in runtime/config.py)
ENV_RETRY_PREFIX = "DTPU_RETRY_"
ENV_CB_PREFIX = "DTPU_CB_"

# transient transport-class failures; typed application errors (see
# runtime/errors.py) deliberately do NOT appear here
RETRYABLE_DEFAULT: Tuple[Type[BaseException], ...] = (
    ConnectionError,
    OSError,
    TimeoutError,
    asyncio.TimeoutError,  # distinct from builtin TimeoutError before py3.11
)


def _spec_dict(spec: Optional[str]) -> Dict[str, str]:
    """``"attempts=4,base=0.05"`` -> {"attempts": "4", "base": "0.05"}."""
    out: Dict[str, str] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        k, sep, v = part.partition("=")
        if not sep:
            raise ValueError(f"bad policy spec fragment {part!r} (want key=value)")
        out[k.strip()] = v.strip()
    return out


def _scope_env(prefix: str, scope: str) -> Dict[str, str]:
    """Layered spec: DTPU_<PREFIX>_DEFAULT overlaid by DTPU_<PREFIX>_<SCOPE>
    (scope dots/dashes become underscores: ``transfer.pull`` ->
    ``DTPU_RETRY_TRANSFER_PULL``)."""
    merged: Dict[str, str] = {}
    for name in ("DEFAULT", scope.upper().replace(".", "_").replace("-", "_")):
        raw = os.environ.get(prefix + name)
        if raw:
            try:
                merged.update(_spec_dict(raw))
            except ValueError as e:
                log.warning("ignoring bad %s%s: %s", prefix, name, e)
    return merged


@dataclasses.dataclass
class RetryPolicy:
    """Bounded retry with exponential backoff + decorrelated jitter.

    ``seed`` pins the jitter schedule (chaos tests assert reproducibility);
    production policies leave it None. ``attempt_timeout_s`` only applies to
    the async path (a sync callable cannot be preempted)."""

    name: str = "default"
    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    attempt_timeout_s: Optional[float] = None
    deadline_s: Optional[float] = None
    retryable: Tuple[Type[BaseException], ...] = RETRYABLE_DEFAULT
    predicate: Optional[Callable[[BaseException], bool]] = None
    seed: Optional[int] = None
    metrics: Optional[M.MetricsScope] = None

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)
        scope = self.metrics if self.metrics is not None else _metrics_scope()
        self._retries = scope.counter(
            M.RETRY_ATTEMPTS_TOTAL, "retry attempts", extra_labels=("policy",)
        )
        self._giveups = scope.counter(
            M.RETRY_GIVEUPS_TOTAL, "retries exhausted", extra_labels=("policy",)
        )

    @classmethod
    def from_env(cls, scope: str, **defaults: Any) -> "RetryPolicy":
        """Policy for ``scope`` from the env catalog, over code defaults.
        Spec keys: attempts, base, max, timeout, deadline."""
        cfg = dict(defaults)
        cfg.setdefault("name", scope)
        spec = _scope_env(ENV_RETRY_PREFIX, scope)
        conv = {
            "attempts": ("max_attempts", int),
            "base": ("base_delay_s", float),
            "max": ("max_delay_s", float),
            "timeout": ("attempt_timeout_s", float),
            "deadline": ("deadline_s", float),
        }
        for key, (field, fn) in conv.items():
            if key in spec:
                try:
                    cfg[field] = fn(spec[key])
                except ValueError:
                    log.warning("bad %s=%r for retry scope %s", key, spec[key], scope)
        return cls(**cfg)

    # -- backoff schedule ----------------------------------------------------
    def is_retryable(self, exc: BaseException) -> bool:
        if self.predicate is not None:
            return bool(self.predicate(exc))
        # typed terminal errors (runtime/errors.py) never retry, even under
        # a broad retryable tuple like (Exception,): a 4xx-class failure or
        # an open circuit cannot be fixed by trying again
        if is_terminal(exc):
            return False
        return isinstance(exc, self.retryable)

    def next_delay(self, prev: Optional[float]) -> float:
        """Decorrelated jitter: min(cap, U(base, 3 * prev)); prev=None seeds
        the chain at base."""
        lo = self.base_delay_s
        hi = max(lo, 3.0 * (prev if prev is not None else lo))
        return min(self.max_delay_s, self._rng.uniform(lo, hi))

    def delays(self):
        """The full backoff schedule for one call (max_attempts - 1 sleeps)."""
        prev: Optional[float] = None
        for _ in range(max(0, self.max_attempts - 1)):
            prev = self.next_delay(prev)
            yield prev

    def _give_up(self, exc: BaseException, attempt: int, t0: float) -> bool:
        if not self.is_retryable(exc):
            return True
        if attempt >= self.max_attempts:
            return True
        if self.deadline_s is not None and time.monotonic() - t0 >= self.deadline_s:
            return True
        return False

    # -- execution -----------------------------------------------------------
    def call(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        t0 = time.monotonic()
        prev: Optional[float] = None
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                if self._give_up(e, attempt, t0):
                    self._giveups.inc(policy=self.name)
                    raise
                prev = self.next_delay(prev)
                self._retries.inc(policy=self.name)
                log.debug(
                    "%s: attempt %d/%d failed (%s); retrying in %.3fs",
                    self.name, attempt, self.max_attempts, e, prev,
                )
                time.sleep(prev)

    async def acall(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        """Async variant; ``fn`` returns an awaitable. Per-attempt timeout is
        enforced with wait_for (a timed-out attempt counts as retryable)."""
        t0 = time.monotonic()
        prev: Optional[float] = None
        attempt = 0
        while True:
            attempt += 1
            try:
                aw = fn(*args, **kwargs)
                if self.attempt_timeout_s is not None:
                    return await asyncio.wait_for(aw, self.attempt_timeout_s)
                return await aw
            except asyncio.CancelledError:
                raise
            except BaseException as e:
                if self._give_up(e, attempt, t0):
                    self._giveups.inc(policy=self.name)
                    raise
                prev = self.next_delay(prev)
                self._retries.inc(policy=self.name)
                log.debug(
                    "%s: attempt %d/%d failed (%s); retrying in %.3fs",
                    self.name, attempt, self.max_attempts, e, prev,
                )
                await asyncio.sleep(prev)


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
_STATE_VALUE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class CircuitOpenError(ConnectionError):
    """Raised (or returned as busy-503 + Retry-After) when a circuit is open."""

    code = "circuit_open"

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            f"circuit {name!r} open; retry after {retry_after_s:.2f}s"
        )
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    """closed/open/half-open breaker over a sliding failure-rate window.

    Trip condition: within ``window_s``, at least ``failure_threshold``
    failures AND a failure rate >= ``failure_rate``. Open rejects for
    ``reset_timeout_s``; then up to ``half_open_max`` concurrent probes run —
    a probe success closes, a probe failure reopens. Thread-safe (no await
    under the lock), so one instance serves sync and asyncio callers alike.
    """

    def __init__(
        self,
        name: str = "default",
        failure_threshold: int = 5,
        failure_rate: float = 0.5,
        window_s: float = 30.0,
        reset_timeout_s: float = 5.0,
        half_open_max: int = 1,
        metrics: Optional[M.MetricsScope] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self.failure_threshold = failure_threshold
        self.failure_rate = failure_rate
        self.window_s = window_s
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max = half_open_max
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._opened_at = 0.0
        self._half_open_inflight = 0
        self._events: Deque[Tuple[float, bool]] = deque()
        scope = metrics if metrics is not None else _metrics_scope()
        self._transitions = scope.counter(
            M.CIRCUIT_TRANSITIONS_TOTAL, "circuit state transitions",
            extra_labels=("policy", "state"),
        )
        self._state_g = scope.gauge(
            M.CIRCUIT_STATE, "circuit state (0 closed, 1 half-open, 2 open)",
            extra_labels=("policy",),
        )
        self._state_g.set(0.0, policy=name)

    @classmethod
    def from_env(cls, scope: str, **defaults: Any) -> "CircuitBreaker":
        """Breaker for ``scope`` from the env catalog. Spec keys: threshold,
        rate, window, reset, half_open."""
        cfg = dict(defaults)
        cfg.setdefault("name", scope)
        spec = _scope_env(ENV_CB_PREFIX, scope)
        conv = {
            "threshold": ("failure_threshold", int),
            "rate": ("failure_rate", float),
            "window": ("window_s", float),
            "reset": ("reset_timeout_s", float),
            "half_open": ("half_open_max", int),
        }
        for key, (field, fn) in conv.items():
            if key in spec:
                try:
                    cfg[field] = fn(spec[key])
                except ValueError:
                    log.warning("bad %s=%r for breaker scope %s", key, spec[key], scope)
        return cls(**cfg)

    # -- state machine -------------------------------------------------------
    def _transition(self, state: str) -> None:
        # lock held by caller
        if state == self._state:
            return
        log.info("circuit %s: %s -> %s", self.name, self._state, state)
        self._state = state
        self._transitions.inc(policy=self.name, state=state)
        self._state_g.set(_STATE_VALUE[state], policy=self.name)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.reset_timeout_s
        ):
            self._transition(HALF_OPEN)
            self._half_open_inflight = 0

    def allow(self) -> bool:
        """True when a call may proceed (and reserves a half-open probe slot)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            self._maybe_half_open()
            if self._state == OPEN:
                return False
            if self._half_open_inflight >= self.half_open_max:
                return False
            self._half_open_inflight += 1
            return True

    def retry_after_s(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(
                0.0, self.reset_timeout_s - (self._clock() - self._opened_at)
            )

    def record(self, ok: bool) -> None:
        with self._lock:
            now = self._clock()
            if self._state == HALF_OPEN:
                if self._half_open_inflight <= 0:
                    # a request admitted before the trip draining now: it is
                    # not the probe and must not drive the transition (a
                    # stale success would close the circuit with no probe
                    # ever reaching a worker)
                    return
                self._half_open_inflight -= 1
                if ok:
                    self._events.clear()
                    self._transition(CLOSED)
                else:
                    self._opened_at = now
                    self._transition(OPEN)
                return
            if self._state == OPEN:
                return  # stale result from before the trip
            self._events.append((now, ok))
            while self._events and now - self._events[0][0] > self.window_s:
                self._events.popleft()
            if ok:
                return
            fails = sum(1 for _, o in self._events if not o)
            if (
                fails >= self.failure_threshold
                and fails / len(self._events) >= self.failure_rate
            ):
                self._opened_at = now
                self._transition(OPEN)

    # -- wrappers ------------------------------------------------------------
    def guard(self) -> None:
        if not self.allow():
            raise CircuitOpenError(self.name, self.retry_after_s())

    def call(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        self.guard()
        try:
            result = fn(*args, **kwargs)
        except BaseException:
            self.record(False)
            raise
        self.record(True)
        return result

    async def acall(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        self.guard()
        try:
            result = await fn(*args, **kwargs)
        except asyncio.CancelledError:
            self.record(True)  # caller went away; not a service failure
            raise
        except BaseException:
            self.record(False)
            raise
        self.record(True)
        return result


# ---------------------------------------------------------------------------
# process-local registries (planes share one policy instance per scope so the
# per-policy metrics aggregate; per-object breakers — e.g. one per worker —
# are constructed directly instead)
# ---------------------------------------------------------------------------

_registry_lock = threading.Lock()
_policies: Dict[str, RetryPolicy] = {}
_breakers: Dict[str, CircuitBreaker] = {}
_default_metrics: Optional[M.MetricsScope] = None


def _metrics_scope() -> M.MetricsScope:
    global _default_metrics
    if _default_metrics is None:
        _default_metrics = M.MetricsScope()
    return _default_metrics


def set_metrics_scope(scope: M.MetricsScope) -> None:
    """Route NEW policies'/breakers' metrics into ``scope`` (e.g. the
    DistributedRuntime's registry so /metrics exposes them)."""
    global _default_metrics
    _default_metrics = scope


def adopt_metrics_scope(scope: M.MetricsScope) -> None:
    """First caller wins: the first DistributedRuntime in a process donates
    its registry so shared policies' retry counters ride that process's
    /metrics instead of a detached private registry."""
    global _default_metrics
    if _default_metrics is None:
        _default_metrics = scope


def retry_policy(scope: str, **defaults: Any) -> RetryPolicy:
    with _registry_lock:
        p = _policies.get(scope)
        if p is None:
            p = _policies[scope] = RetryPolicy.from_env(scope, **defaults)
        return p


def circuit_breaker(scope: str, **defaults: Any) -> CircuitBreaker:
    with _registry_lock:
        b = _breakers.get(scope)
        if b is None:
            b = _breakers[scope] = CircuitBreaker.from_env(scope, **defaults)
        return b


def reset_registries() -> None:
    """Drop cached policies/breakers (tests; env spec changes)."""
    with _registry_lock:
        _policies.clear()
        _breakers.clear()
