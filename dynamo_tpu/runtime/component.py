"""Component model: Namespace -> Component -> Endpoint -> Instance.

Analog of the reference's component hierarchy (lib/runtime/src/component.rs)
and its PushRouter / RouterMode client-side selection
(lib/runtime/src/pipeline/network/egress/push_router.rs:41,76-83).

A worker *serves* an endpoint (registers an Instance in the discovery store
under ``v1/instances/...`` tied to its lease); a frontend builds a *Client*
on the same endpoint which watches that prefix and routes requests to live
instances over the request plane.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import random
import uuid
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, List, Optional

from .discovery.store import EventType, KVStore, Watcher
from .engine import Context
from .logging import get_logger
from .request_plane.tcp import Handler, NoResponders, TcpClient, TcpRequestServer
from .tasks import spawn_bg

log = get_logger("runtime.component")

INSTANCES_PREFIX = "v1/instances"


def instance_key(namespace: str, component: str, endpoint: str, instance_id: int) -> str:
    return f"{INSTANCES_PREFIX}/{namespace}/{component}/{endpoint}/{instance_id:016x}"


def new_instance_id() -> int:
    return uuid.uuid4().int & ((1 << 63) - 1)


@dataclasses.dataclass
class Instance:
    """A live serving unit (reference: lib/runtime/src/component.rs:88)."""

    instance_id: int
    namespace: str
    component: str
    endpoint: str
    address: str          # request-plane address, e.g. "127.0.0.1:4431"
    transport: str = "tcp"
    metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_obj(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "Instance":
        return cls(**obj)


class RouterMode(enum.Enum):
    ROUND_ROBIN = "round-robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"


class Namespace:
    def __init__(self, runtime: "DistributedRuntimeBase", name: str):
        self.runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self, name)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Namespace({self.name})"


class Component:
    def __init__(self, namespace: Namespace, name: str):
        self.namespace = namespace
        self.name = name

    @property
    def runtime(self) -> "DistributedRuntimeBase":
        return self.namespace.runtime

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)

    @property
    def path(self) -> str:
        return f"{self.namespace.name}/{self.name}"

    def __repr__(self) -> str:  # pragma: no cover
        return f"Component({self.path})"


class Endpoint:
    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name

    @property
    def runtime(self) -> "DistributedRuntimeBase":
        return self.component.runtime

    @property
    def path(self) -> str:
        return f"{self.component.path}/{self.name}"

    @property
    def subject_prefix(self) -> str:
        ns = self.component.namespace.name
        return f"{INSTANCES_PREFIX}/{ns}/{self.component.name}/{self.name}/"

    async def serve(
        self,
        handler: Handler,
        instance_id: Optional[int] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> "ServedEndpoint":
        """Start a request-plane server for ``handler`` and register it."""
        rt = self.runtime
        iid = instance_id if instance_id is not None else new_instance_id()
        if getattr(rt.config, "request_plane", "tcp") == "http":
            from .request_plane.http import HttpRequestServer

            server = HttpRequestServer(handler, host=rt.config.host_ip)
        else:
            server = TcpRequestServer(handler, host=rt.config.host_ip)
        address = await server.start()
        inst = Instance(
            instance_id=iid,
            namespace=self.component.namespace.name,
            component=self.component.name,
            endpoint=self.name,
            address=address,
            metadata=metadata or {},
        )
        key = instance_key(inst.namespace, inst.component, inst.endpoint, iid)
        await rt.store.put_obj(key, inst.to_obj(), rt.lease_id)
        log.info("serving %s as instance %016x at %s", self.path, iid, address)
        served = ServedEndpoint(self, inst, server, key)
        getattr(rt, "served", []).append(served)
        return served

    async def client(self, router_mode: RouterMode = RouterMode.ROUND_ROBIN) -> "Client":
        client = Client(self, router_mode)
        await client.start()
        return client


class ServedEndpoint:
    def __init__(self, endpoint: Endpoint, instance: Instance, server: TcpRequestServer, key: str):
        self.endpoint = endpoint
        self.instance = instance
        self.server = server
        self._key = key
        # additional lease-attached keys (e.g. model cards) that live and die
        # with this endpoint: key -> msgpack-able object
        self.extra_objs: Dict[str, Any] = {}

    @property
    def instance_id(self) -> int:
        return self.instance.instance_id

    @property
    def address(self) -> str:
        return self.instance.address

    async def update_metadata(self, metadata: Dict[str, Any]) -> None:
        self.instance.metadata.update(metadata)
        await self.endpoint.runtime.store.put_obj(
            self._key, self.instance.to_obj(), self.endpoint.runtime.lease_id
        )

    async def publish_extra(self, key: str, obj: Any) -> None:
        rt = self.endpoint.runtime
        self.extra_objs[key] = obj
        await rt.store.put_obj(key, obj, rt.lease_id)

    async def stop(self, graceful_timeout_s: float = 5.0) -> None:
        rt = self.endpoint.runtime
        if self in getattr(rt, "served", []):
            rt.served.remove(self)
        for key in self.extra_objs:
            await rt.store.delete(key)
        await rt.store.delete(self._key)
        await self.server.stop(graceful_timeout_s)


# Selector signature for KV routing: given the request and the live instances,
# return the chosen instance_id (overlap metadata travels inside the request).
KvSelector = Callable[[Any, List[Instance]], Awaitable[int]]


class _TaggedStream:
    """Response stream that knows which instance serves it.

    Transport errors get ``instance_id`` stamped on them mid-iteration, and
    the attribute itself lets consumers (the migration operator) attribute a
    CLEAN stream EOF — a worker teardown that closes the stream without a
    finish frame raises no exception, yet the retry still must exclude the
    dead instance."""

    def __init__(self, stream: AsyncIterator[Any], instance_id: int):
        self._stream = stream
        self.instance_id = instance_id

    def __aiter__(self) -> "_TaggedStream":
        return self

    async def __anext__(self) -> Any:
        try:
            return await self._stream.__anext__()
        except StopAsyncIteration:
            raise
        except (NoResponders, ConnectionError) as e:
            if getattr(e, "instance_id", None) is None:
                e.instance_id = self.instance_id  # type: ignore[attr-defined]
            raise


class Client:
    """Endpoint client with live instance tracking + push routing."""

    def __init__(self, endpoint: Endpoint, router_mode: RouterMode = RouterMode.ROUND_ROBIN):
        self.endpoint = endpoint
        self.router_mode = router_mode
        self.instances: Dict[int, Instance] = {}
        self._rr_index = 0
        self._watcher: Optional[Watcher] = None
        self._watch_task: Optional[asyncio.Task] = None
        self._rt = endpoint.runtime
        self._instances_event = asyncio.Event()
        self.kv_selector: Optional[KvSelector] = None

    async def start(self) -> None:
        store = self.endpoint.runtime.store
        self._watcher = await store.watch(self.endpoint.subject_prefix)
        # spawn_bg: a watch loop that dies must log — a silently-dead loop
        # leaves this client routing to a stale instance table forever
        self._watch_task = spawn_bg(self._watch_loop())

    async def _watch_loop(self) -> None:
        assert self._watcher is not None
        async for ev in self._watcher:
            try:
                self._apply_event(ev)
            except Exception:
                # per-event isolation: one corrupt instance record must not
                # kill the loop and freeze the instance table (every later
                # PUT/DELETE would be lost while requests keep routing on
                # stale entries)
                log.exception(
                    "%s: bad instance event (%s)", self.endpoint.path, ev.key
                )

    def _apply_event(self, ev) -> None:
        import msgpack

        if ev.type == EventType.PUT and ev.value is not None:
            inst = Instance.from_obj(msgpack.unpackb(ev.value, raw=False))
            self.instances[inst.instance_id] = inst
            self._instances_event.set()
        elif ev.type == EventType.DELETE:
            iid_hex = ev.key.rsplit("/", 1)[-1]
            try:
                self.instances.pop(int(iid_hex, 16), None)
            except ValueError:
                pass
            if not self.instances:
                self._instances_event.clear()

    async def wait_for_instances(self, n: int = 1, timeout: float = 30.0) -> List[Instance]:
        deadline = asyncio.get_event_loop().time() + timeout
        while len(self.instances) < n:
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"{self.endpoint.path}: {len(self.instances)}/{n} instances after {timeout}s"
                )
            try:
                await asyncio.wait_for(self._instances_event.wait(), remaining)
            except asyncio.TimeoutError:
                pass
            if len(self.instances) < n:
                self._instances_event.clear()
        return list(self.instances.values())

    def instance_ids(self) -> List[int]:
        return sorted(self.instances)

    # -- selection ----------------------------------------------------------
    def _select(self, request: Any, instance_id: Optional[int]) -> Instance:
        if not self.instances:
            raise NoResponders(f"no instances for {self.endpoint.path}")
        if instance_id is not None:
            inst = self.instances.get(instance_id)
            if inst is None:
                raise NoResponders(f"instance {instance_id:016x} gone")
            return inst
        ids = sorted(self.instances)
        if self.router_mode == RouterMode.RANDOM:
            return self.instances[random.choice(ids)]
        # ROUND_ROBIN default (KV mode resolves instance_id upstream)
        inst = self.instances[ids[self._rr_index % len(ids)]]
        self._rr_index += 1
        return inst

    async def generate(
        self,
        request: Any,
        context: Optional[Context] = None,
        instance_id: Optional[int] = None,
    ) -> AsyncIterator[Any]:
        """Route a request and stream back responses.

        Failures carry the chosen ``instance_id`` (set on the exception), at
        call time AND mid-stream: the migration operator excludes that worker
        on retry — without the tag, a "connection lost" mid-stream retry
        could round-robin straight back onto the dead worker (reference
        excludes on any mid-stream engine loss, lib/llm/src/migration.rs).
        """
        if self.router_mode == RouterMode.KV and instance_id is None and self.kv_selector:
            instance_id = await self.kv_selector(request, list(self.instances.values()))
        inst = self._select(request, instance_id)
        try:
            stream = await self._rt.plane_client(inst.address).call(
                inst.address, request, context
            )
        except (NoResponders, ConnectionError) as e:
            if getattr(e, "instance_id", None) is None:
                e.instance_id = inst.instance_id  # type: ignore[attr-defined]
            raise
        return _TaggedStream(stream, inst.instance_id)

    async def stop(self) -> None:
        if self._watcher is not None:
            self._watcher.cancel()
        if self._watch_task is not None:
            self._watch_task.cancel()


class DistributedRuntimeBase:
    """Interface Namespace/Component/Endpoint expect; impl in distributed.py."""

    store: KVStore
    tcp_client: TcpClient
    lease_id: Optional[str]
    config: Any

    def plane_client(self, address: str):
        """Transport by address scheme: http(s):// -> HTTP plane, else TCP."""
        if address.startswith("http"):
            return self.http_client
        return self.tcp_client

    def namespace(self, name: str) -> Namespace:
        return Namespace(self, name)
