"""HTTP request plane: the reference's HTTP/2 transport alternative.

Analog of lib/runtime's pluggable request plane (the reference offers NATS,
TCP and an HTTP/2 gRPC-like plane; SURVEY §2.6). Same streaming-RPC contract
as request_plane/tcp.py — one POST per request, the response streamed as
``u32 length || msgpack`` frames over chunked transfer encoding:

    POST /rpc          body: msgpack request           -> frame stream
    POST /cancel/{id}                                  -> {"ok": true}
    GET  /ping                                          -> {"ok": true}

Request ids ride the ``x-dtpu-request-id`` header so cancel is addressable
mid-stream from a second connection (HTTP has no in-band reverse channel).
Addresses are ``http://host:port``; the component layer picks this plane by
scheme (component.py).
"""

from __future__ import annotations

import asyncio
import struct
import uuid
from typing import Any, AsyncIterator, Dict, Optional

import msgpack
from aiohttp import ClientSession, ClientTimeout, TCPConnector, web
from aiohttp.client_exceptions import ClientConnectorError, ClientError

from ..engine import Context
from ..faults import FAULTS
from ..logging import get_logger
from ..tasks import spawn_bg
from .tcp import Handler, NoResponders, RequestPlaneError

log = get_logger("runtime.http_plane")

_LEN = struct.Struct(">I")

REQUEST_ID_HEADER = "x-dtpu-request-id"


def _frame(obj: Dict[str, Any]) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    return _LEN.pack(len(body)) + body


class HttpRequestServer:
    """Same surface as TcpRequestServer (start/stop/address/inflight)."""

    def __init__(self, handler: Handler, host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._host = host
        self._port = port
        self._inflight: Dict[str, Context] = {}
        self._runner: Optional[web.AppRunner] = None

    @property
    def address(self) -> str:
        return f"http://{self._host}:{self._port}"

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    async def start(self) -> str:
        app = web.Application(client_max_size=512 * 1024 * 1024)
        app.router.add_post("/rpc", self._rpc)
        app.router.add_post("/cancel/{rid}", self._cancel)
        app.router.add_get("/ping", self._ping)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        self._port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.debug("http request server listening on %s", self.address)
        return self.address

    async def stop(self, graceful_timeout_s: float = 5.0) -> None:
        deadline = asyncio.get_event_loop().time() + graceful_timeout_s
        while self._inflight and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.05)
        for ctx in self._inflight.values():
            ctx.kill()
        if self._runner is not None:
            await self._runner.cleanup()

    async def _ping(self, request: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    async def _cancel(self, request: web.Request) -> web.Response:
        ctx = self._inflight.get(request.match_info["rid"])
        if ctx is not None:
            ctx.stop_generating()
        return web.json_response({"ok": ctx is not None})

    async def _rpc(self, request: web.Request) -> web.StreamResponse:
        rid = request.headers.get(REQUEST_ID_HEADER) or uuid.uuid4().hex
        body = msgpack.unpackb(await request.read(), raw=False)
        ctx = Context(rid)
        self._inflight[rid] = ctx
        resp = web.StreamResponse(headers={"Content-Type": "application/x-dtpu-frames"})
        await resp.prepare(request)
        try:
            async for item in self._handler(body, ctx):
                if ctx.is_killed():
                    break
                await resp.write(_frame({"t": "item", "body": item}))
            await resp.write(_frame({"t": "end"}))
        except (ConnectionResetError, asyncio.CancelledError):
            ctx.kill()
        except Exception as e:
            log.exception("handler error for request %s", rid[:8])
            try:
                await resp.write(_frame({
                    "t": "err", "error": str(e),
                    "code": getattr(e, "code", "internal"),
                }))
            except ConnectionResetError:
                pass
        finally:
            self._inflight.pop(rid, None)
        try:
            await resp.write_eof()
        except ConnectionResetError:
            pass
        return resp


class HttpClient:
    """Same surface as TcpClient (call/ping/close); pooled sessions."""

    def __init__(self):
        self._session: Optional[ClientSession] = None

    def _sess(self) -> ClientSession:
        if self._session is None or self._session.closed:
            self._session = ClientSession(
                connector=TCPConnector(limit=0),
                timeout=ClientTimeout(total=None, connect=5.0),
            )
        return self._session

    async def call(
        self, address: str, request: Any, context: Optional[Context] = None
    ) -> AsyncIterator[Any]:
        ctx = context or Context()
        rid = uuid.uuid4().hex
        sess = self._sess()
        try:
            await FAULTS.ainject("request_plane.send")
        except ConnectionError as e:
            raise NoResponders(f"send {address}: {e}") from e
        try:
            resp = await sess.post(
                address.rstrip("/") + "/rpc",
                data=msgpack.packb(request, use_bin_type=True),
                headers={REQUEST_ID_HEADER: rid},
            )
        except (ClientConnectorError, OSError) as e:
            raise NoResponders(f"connect {address}: {e}") from e

        def on_cancel() -> None:
            spawn_bg(self._send_cancel(address, rid))

        ctx.on_cancel(on_cancel)

        async def stream() -> AsyncIterator[Any]:
            buf = b""
            try:
                async for chunk in resp.content.iter_any():
                    buf += chunk
                    while len(buf) >= _LEN.size:
                        (n,) = _LEN.unpack(buf[:_LEN.size])
                        if len(buf) < _LEN.size + n:
                            break
                        msg = msgpack.unpackb(buf[_LEN.size:_LEN.size + n], raw=False)
                        buf = buf[_LEN.size + n:]
                        t = msg.get("t")
                        if t == "item":
                            yield msg.get("body")
                        elif t == "end":
                            return
                        elif t == "err":
                            code = msg.get("code", "internal")
                            if code == "no_responders":
                                raise NoResponders(msg.get("error", ""))
                            raise RequestPlaneError(msg.get("error", ""), code)
                # server closed without an end frame: treat as gone
                raise NoResponders(f"{address}: stream ended prematurely")
            except (ClientError, ConnectionResetError) as e:
                raise NoResponders(f"{address}: {e}") from e
            finally:
                resp.close()

        return stream()

    async def _send_cancel(self, address: str, rid: str) -> None:
        try:
            async with self._sess().post(
                address.rstrip("/") + f"/cancel/{rid}"
            ) as r:
                await r.read()
        except (ClientError, OSError):
            pass

    async def ping(self, address: str, timeout: float = 2.0) -> float:
        t0 = asyncio.get_running_loop().time()
        try:
            async with self._sess().get(
                address.rstrip("/") + "/ping",
                timeout=ClientTimeout(total=timeout),
            ) as r:
                if r.status != 200:
                    raise NoResponders(f"{address}: ping {r.status}")
                await r.read()
        except (ClientError, OSError, asyncio.TimeoutError) as e:
            raise NoResponders(f"ping {address}: {e}") from e
        return asyncio.get_running_loop().time() - t0

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
