"""TCP request plane: multiplexed, streaming, cancellable RPC.

Analog of the reference's default TCP request plane with its two-part msgpack
codec (lib/runtime/src/pipeline/network/tcp/{server,client}.rs,
codec/two_part.rs). One TCP connection carries many concurrent requests; each
frame is ``u32 length || msgpack map``. Response streams are sequences of
``item`` frames terminated by ``end`` / ``err``; the client can send ``cancel``
mid-stream and the server propagates it into the handler's Context.

Frame schema::

    {"t": "req",    "id": str, "hdr": {..}, "body": any}
    {"t": "item",   "id": str, "body": any}
    {"t": "end",    "id": str}
    {"t": "err",    "id": str, "error": str, "code": str}
    {"t": "cancel", "id": str}
    {"t": "ping"} / {"t": "pong"}

msgpack carries ``bytes`` natively, so tensor payloads ride as binary fields
without a separate framing layer.
"""

from __future__ import annotations

import asyncio
import struct
import uuid
from typing import Any, AsyncIterator, Callable, Dict, Optional

import msgpack

from ..engine import Context
from ..faults import FAULTS
from ..logging import get_logger
from ..resilience import retry_policy
from ..tasks import spawn_bg

log = get_logger("runtime.tcp")

_LEN = struct.Struct(">I")
MAX_FRAME = 512 * 1024 * 1024  # 512 MB: KV-block payloads can be large


class RequestPlaneError(Exception):
    def __init__(self, message: str, code: str = "internal"):
        super().__init__(message)
        self.code = code


class NoResponders(RequestPlaneError):
    """Target instance is gone (connection refused / reset before reply).

    The migration operator retries on this, mirroring the reference's retry on
    NATS NoResponders (lib/llm/src/migration.rs:9-11)."""

    def __init__(self, message: str = "no responders"):
        super().__init__(message, code="no_responders")


async def _read_frame(reader: asyncio.StreamReader) -> Optional[Dict[str, Any]]:
    try:
        hdr = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = _LEN.unpack(hdr)
    if length > MAX_FRAME:
        raise RequestPlaneError(f"frame too large: {length}")
    try:
        payload = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(payload, raw=False)


def _write_frame(writer: asyncio.StreamWriter, msg: Dict[str, Any]) -> None:
    payload = msgpack.packb(msg, use_bin_type=True)
    writer.write(_LEN.pack(len(payload)) + payload)


Handler = Callable[[Any, Context], AsyncIterator[Any]]


class TcpRequestServer:
    """Serves a single handler; one instance per (endpoint, worker)."""

    def __init__(self, handler: Handler, host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._host = host
        self._port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._inflight: Dict[str, Context] = {}
        self._conn_tasks: set = set()

    @property
    def address(self) -> str:
        assert self._server is not None, "server not started"
        sock = self._server.sockets[0]
        host, port = sock.getsockname()[:2]
        return f"{host}:{port}"

    @property
    def inflight(self) -> int:
        return len(self._inflight)

    async def start(self) -> str:
        self._server = await asyncio.start_server(self._on_conn, self._host, self._port)
        log.debug("tcp request server listening on %s", self.address)
        return self.address

    async def stop(self, graceful_timeout_s: float = 5.0) -> None:
        if self._server is not None:
            self._server.close()
        deadline = asyncio.get_event_loop().time() + graceful_timeout_s
        while self._inflight and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.05)
        for ctx in self._inflight.values():
            ctx.kill()
        # py3.12 Server.wait_closed() blocks until every connection handler
        # returns, and pooled clients hold connections open — cancel them first
        for t in list(self._conn_tasks):
            t.cancel()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                pass

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        write_lock = asyncio.Lock()
        req_tasks: Dict[str, asyncio.Task] = {}

        async def send(msg: Dict[str, Any]) -> None:
            async with write_lock:
                _write_frame(writer, msg)
                await writer.drain()

        async def run_request(rid: str, body: Any) -> None:
            ctx = Context(rid)
            self._inflight[rid] = ctx
            try:
                async for item in self._handler(body, ctx):
                    if ctx.is_killed():
                        break
                    await send({"t": "item", "id": rid, "body": item})
                await send({"t": "end", "id": rid})
            except (ConnectionResetError, BrokenPipeError):
                ctx.kill()
            except Exception as e:  # handler error -> err frame
                log.exception("handler error for request %s", rid[:8])
                code = getattr(e, "code", "internal")
                try:
                    await send({"t": "err", "id": rid, "error": str(e), "code": code})
                except (ConnectionResetError, BrokenPipeError):
                    pass
            finally:
                self._inflight.pop(rid, None)
                req_tasks.pop(rid, None)

        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        try:
            while True:
                msg = await _read_frame(reader)
                if msg is None:
                    break
                t = msg.get("t")
                if t == "req":
                    rid = msg["id"]
                    req_tasks[rid] = asyncio.create_task(run_request(rid, msg.get("body")))
                elif t == "cancel":
                    ctx = self._inflight.get(msg["id"])
                    if ctx is not None:
                        ctx.stop_generating()
                elif t == "ping":
                    await send({"t": "pong"})
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            # client went away: kill everything it had in flight on this conn
            for rid, rt in list(req_tasks.items()):
                ctx = self._inflight.get(rid)
                if ctx is not None:
                    ctx.kill()
                rt.cancel()
            if task is not None:
                self._conn_tasks.discard(task)
            writer.close()


class _Conn:
    """One multiplexed client connection."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.write_lock = asyncio.Lock()
        self.streams: Dict[str, asyncio.Queue] = {}
        self.pong_waiters: list = []  # Futures resolved FIFO by pong frames
        # pongs owed to pings that already timed out: discarded instead of
        # resolving the NEXT ping's future (a wedged-but-alive server would
        # otherwise look healthy forever via off-by-one pong credit)
        self.stale_pongs = 0
        self.reader_task: Optional[asyncio.Task] = None
        self.closed = False

    async def send(self, msg: Dict[str, Any]) -> None:
        async with self.write_lock:
            _write_frame(self.writer, msg)
            await self.writer.drain()

    async def read_loop(self) -> None:
        try:
            while True:
                msg = await _read_frame(self.reader)
                if msg is None:
                    break
                if msg.get("t") == "pong":
                    if self.stale_pongs > 0:
                        self.stale_pongs -= 1
                        continue
                    while self.pong_waiters:
                        fut = self.pong_waiters.pop(0)
                        if not fut.done():
                            fut.set_result(True)
                            break
                    continue
                rid = msg.get("id")
                q = self.streams.get(rid)
                if q is not None:
                    q.put_nowait(msg)
        except asyncio.CancelledError:
            pass
        finally:
            self.closed = True
            for q in self.streams.values():
                q.put_nowait({"t": "err", "error": "connection lost", "code": "no_responders"})
            for fut in self.pong_waiters:
                if not fut.done():
                    fut.set_result(False)
            self.pong_waiters.clear()
            self.writer.close()


class TcpClient:
    """Connection-pooled client; one shared instance per process is typical."""

    def __init__(self):
        self._conns: Dict[str, _Conn] = {}
        self._conn_locks: Dict[str, asyncio.Lock] = {}

    async def _get_conn(self, address: str) -> _Conn:
        conn = self._conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        lock = self._conn_locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._conns.get(address)
            if conn is not None and not conn.closed:
                return conn
            host, port_s = address.rsplit(":", 1)

            async def connect():
                await FAULTS.ainject("request_plane.connect")
                return await asyncio.open_connection(host, int(port_s))

            try:
                # shared policy (scope request_plane.connect): one quick
                # retry absorbs a worker restarting its listener; a truly
                # dead target still surfaces as NoResponders in ~base delay
                reader, writer = await retry_policy(
                    "request_plane.connect",
                    max_attempts=2, base_delay_s=0.02, max_delay_s=0.2,
                ).acall(connect)
            except (ConnectionRefusedError, OSError) as e:
                raise NoResponders(f"connect {address}: {e}") from e
            conn = _Conn(reader, writer)
            conn.reader_task = asyncio.create_task(conn.read_loop())
            self._conns[address] = conn
            return conn

    async def call(
        self, address: str, request: Any, context: Optional[Context] = None
    ) -> AsyncIterator[Any]:
        """Issue a request; yields response items as they stream back."""
        ctx = context or Context()
        conn = await self._get_conn(address)
        rid = uuid.uuid4().hex
        q: asyncio.Queue = asyncio.Queue()
        conn.streams[rid] = q

        cancelled_sent = False

        async def send_cancel() -> None:
            nonlocal cancelled_sent
            if not cancelled_sent and not conn.closed:
                cancelled_sent = True
                try:
                    await conn.send({"t": "cancel", "id": rid})
                except (ConnectionResetError, BrokenPipeError, RuntimeError):
                    pass

        def on_cancel() -> None:
            spawn_bg(send_cancel())

        ctx.on_cancel(on_cancel)
        try:
            await FAULTS.ainject("request_plane.send")
            await conn.send({"t": "req", "id": rid, "body": request})
        except ConnectionError as e:  # covers reset/broken-pipe/injected drop
            conn.streams.pop(rid, None)
            raise NoResponders(f"send {address}: {e}") from e

        async def stream() -> AsyncIterator[Any]:
            try:
                while True:
                    msg = await q.get()
                    t = msg.get("t")
                    if t == "item":
                        yield msg.get("body")
                    elif t == "end":
                        return
                    elif t == "err":
                        code = msg.get("code", "internal")
                        if code == "no_responders":
                            raise NoResponders(msg.get("error", ""))
                        raise RequestPlaneError(msg.get("error", ""), code)
            finally:
                conn.streams.pop(rid, None)

        return stream()

    async def ping(self, address: str, timeout: float = 2.0) -> float:
        """Round-trip a ping through the full request-plane path (connect,
        frame codec, server read loop). Returns RTT seconds; raises
        NoResponders on connect failure or pong timeout. This is the canary
        probe primitive (reference: lib/runtime/src/health_check.rs)."""
        t0 = asyncio.get_running_loop().time()
        conn = await self._get_conn(address)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        conn.pong_waiters.append(fut)
        sent = False
        try:
            await conn.send({"t": "ping"})
            sent = True
            ok = await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, ConnectionResetError, BrokenPipeError) as e:
            if fut in conn.pong_waiters:
                conn.pong_waiters.remove(fut)
                if sent and isinstance(e, asyncio.TimeoutError):
                    # our pong may still arrive late; it must be discarded,
                    # not credited to the next ping
                    conn.stale_pongs += 1
            raise NoResponders(f"ping {address}: {e!r}") from e
        if not ok:
            raise NoResponders(f"ping {address}: connection lost")
        return asyncio.get_running_loop().time() - t0

    async def close(self) -> None:
        for conn in self._conns.values():
            if conn.reader_task is not None:
                conn.reader_task.cancel()
            conn.writer.close()
        self._conns.clear()
