"""Lease-based KV store for discovery: memory + file backends.

Analog of the reference's pluggable storage/discovery layer: etcd by default
with file/mem fallbacks (lib/runtime/src/storage/kv/{etcd,file,mem}.rs and
lib/runtime/src/discovery/kv_store.rs). No etcd client ships in this image, so
the file backend is our cross-process default: one file per key plus lease
heartbeat files; watchers poll and synthesize PUT/DELETE events, and keys whose
lease heartbeat has gone stale are reaped as if their owner died — giving the
same crash-detection semantics as etcd lease expiry.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import os
import re
import time
import urllib.parse
import uuid
from typing import AsyncIterator, Dict, List, Optional, Tuple

import msgpack

from ..logging import get_logger

log = get_logger("runtime.discovery")

DEFAULT_LEASE_TTL_S = 10.0
_WATCH_POLL_S = 0.1
_TMP_RE = re.compile(r"\.__tmp__\.\d+\.[0-9a-f]{6}$")


class EventType(enum.Enum):
    PUT = "put"
    DELETE = "delete"


@dataclasses.dataclass
class WatchEvent:
    type: EventType
    key: str
    value: Optional[bytes]


@dataclasses.dataclass
class Lease:
    id: str
    ttl_s: float


class KVStore:
    """Interface: put/get/delete/list_prefix/watch + lease lifecycle."""

    async def put(self, key: str, value: bytes, lease_id: Optional[str] = None) -> None:
        raise NotImplementedError

    async def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    async def delete(self, key: str) -> None:
        raise NotImplementedError

    async def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        raise NotImplementedError

    async def watch(self, prefix: str) -> "Watcher":
        """Snapshot-then-stream: the watcher first yields PUT events for every
        existing key under the prefix, then live events."""
        raise NotImplementedError

    # -- leases -------------------------------------------------------------
    async def create_lease(self, ttl_s: float = DEFAULT_LEASE_TTL_S) -> Lease:
        raise NotImplementedError

    async def keep_alive(self, lease_id: str) -> bool:
        raise NotImplementedError

    async def revoke_lease(self, lease_id: str) -> None:
        """Revoking deletes every key attached to the lease (etcd semantics)."""
        raise NotImplementedError

    async def close(self) -> None:
        pass

    # convenience -----------------------------------------------------------
    async def put_obj(self, key: str, obj, lease_id: Optional[str] = None) -> None:
        await self.put(key, msgpack.packb(obj, use_bin_type=True), lease_id)

    async def get_obj(self, key: str):
        raw = await self.get(key)
        return None if raw is None else msgpack.unpackb(raw, raw=False)

    async def list_obj(self, prefix: str) -> Dict[str, object]:
        """``list_prefix`` with msgpack decode; keys whose bytes do not
        decode are skipped (a foreign writer under our prefix must not
        break every scan — the global KV directory's hot lookup path)."""
        out: Dict[str, object] = {}
        for k, raw in (await self.list_prefix(prefix)).items():
            try:
                out[k] = msgpack.unpackb(raw, raw=False)
            except (ValueError, msgpack.exceptions.ExtraData,
                    msgpack.exceptions.FormatError,
                    msgpack.exceptions.StackError):
                continue
        return out


class Watcher:
    """Async stream of WatchEvents with explicit cancel."""

    def __init__(self):
        self._queue: asyncio.Queue = asyncio.Queue()
        self._closed = False

    def _emit(self, ev: WatchEvent) -> None:
        if not self._closed:
            self._queue.put_nowait(ev)

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        if self._closed and self._queue.empty():
            raise StopAsyncIteration
        ev = await self._queue.get()
        if ev is None:
            raise StopAsyncIteration
        return ev

    def cancel(self) -> None:
        self._closed = True
        self._queue.put_nowait(None)


# ---------------------------------------------------------------------------
# In-memory backend
# ---------------------------------------------------------------------------


class MemKVStore(KVStore):
    """Single-process store; watchers get events synchronously on mutation."""

    def __init__(self):
        self._data: Dict[str, Tuple[bytes, Optional[str]]] = {}
        self._leases: Dict[str, float] = {}  # lease_id -> deadline (monotonic)
        self._lease_ttl: Dict[str, float] = {}
        self._watchers: List[Tuple[str, Watcher]] = []
        self._reaper: Optional[asyncio.Task] = None

    def _notify(self, ev: WatchEvent) -> None:
        for prefix, w in list(self._watchers):
            if ev.key.startswith(prefix):
                w._emit(ev)

    async def put(self, key: str, value: bytes, lease_id: Optional[str] = None) -> None:
        self._data[key] = (value, lease_id)
        self._notify(WatchEvent(EventType.PUT, key, value))

    async def get(self, key: str) -> Optional[bytes]:
        item = self._data.get(key)
        return None if item is None else item[0]

    async def delete(self, key: str) -> None:
        if key in self._data:
            del self._data[key]
            self._notify(WatchEvent(EventType.DELETE, key, None))

    async def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        return {k: v for k, (v, _) in self._data.items() if k.startswith(prefix)}

    async def watch(self, prefix: str) -> Watcher:
        w = Watcher()
        for k, (v, _) in sorted(self._data.items()):
            if k.startswith(prefix):
                w._emit(WatchEvent(EventType.PUT, k, v))
        self._watchers.append((prefix, w))
        return w

    async def create_lease(self, ttl_s: float = DEFAULT_LEASE_TTL_S) -> Lease:
        lease_id = uuid.uuid4().hex
        self._leases[lease_id] = time.monotonic() + ttl_s
        self._lease_ttl[lease_id] = ttl_s
        if self._reaper is None or self._reaper.done():
            self._reaper = asyncio.create_task(self._reap_loop())
        return Lease(lease_id, ttl_s)

    async def keep_alive(self, lease_id: str) -> bool:
        if lease_id not in self._leases:
            return False
        self._leases[lease_id] = time.monotonic() + self._lease_ttl[lease_id]
        return True

    async def revoke_lease(self, lease_id: str) -> None:
        self._leases.pop(lease_id, None)
        self._lease_ttl.pop(lease_id, None)
        for key in [k for k, (_, lid) in self._data.items() if lid == lease_id]:
            await self.delete(key)

    async def _reap_loop(self) -> None:
        try:
            while self._leases:
                now = time.monotonic()
                expired = [lid for lid, dl in self._leases.items() if dl < now]
                for lid in expired:
                    log.debug("lease %s expired", lid[:8])
                    await self.revoke_lease(lid)
                await asyncio.sleep(0.2)
        except asyncio.CancelledError:
            pass

    async def close(self) -> None:
        if self._reaper is not None:
            self._reaper.cancel()
        for _, w in self._watchers:
            w.cancel()


# ---------------------------------------------------------------------------
# File backend (cross-process, no external services)
# ---------------------------------------------------------------------------


def _enc(key: str) -> str:
    return urllib.parse.quote(key, safe="")


def _dec(name: str) -> str:
    return urllib.parse.unquote(name)


class FileKVStore(KVStore):
    """Directory-backed store. Layout::

        <root>/keys/<urlencoded-key>    msgpack {v: bytes, lease: str|None}
        <root>/leases/<lease_id>        msgpack {hb: float, ttl: float}

    Liveness: a key with a lease is visible only while its lease file's
    heartbeat is fresh (hb + ttl + grace > now, wall clock — all participants
    share the host/filesystem). Watchers poll and diff.
    """

    GRACE_S = 1.0

    def __init__(self, root: str):
        self.root = root
        self._keys_dir = os.path.join(root, "keys")
        self._leases_dir = os.path.join(root, "leases")
        os.makedirs(self._keys_dir, exist_ok=True)
        os.makedirs(self._leases_dir, exist_ok=True)
        self._watch_tasks: List[asyncio.Task] = []
        self._own_leases: Dict[str, float] = {}

    # -- low level ----------------------------------------------------------
    def _write_atomic(self, path: str, data: bytes) -> None:
        tmp = f"{path}.__tmp__.{os.getpid()}.{uuid.uuid4().hex[:6]}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def _lease_alive(self, lease_id: Optional[str]) -> bool:
        if lease_id is None:
            return True
        path = os.path.join(self._leases_dir, lease_id)
        try:
            with open(path, "rb") as f:
                rec = msgpack.unpackb(f.read(), raw=False)
        except (FileNotFoundError, ValueError):
            return False
        return rec["hb"] + rec["ttl"] + self.GRACE_S > time.time()

    def _read_key(self, key: str) -> Optional[bytes]:
        path = os.path.join(self._keys_dir, _enc(key))
        try:
            with open(path, "rb") as f:
                rec = msgpack.unpackb(f.read(), raw=False)
        except (FileNotFoundError, ValueError):
            return None
        if not self._lease_alive(rec.get("lease")):
            try:
                os.unlink(path)  # reap key owned by a dead lease
            except FileNotFoundError:
                pass
            return None
        return rec["v"]

    # -- KVStore ------------------------------------------------------------
    async def put(self, key: str, value: bytes, lease_id: Optional[str] = None) -> None:
        rec = msgpack.packb({"v": value, "lease": lease_id}, use_bin_type=True)
        self._write_atomic(os.path.join(self._keys_dir, _enc(key)), rec)

    async def get(self, key: str) -> Optional[bytes]:
        return self._read_key(key)

    async def delete(self, key: str) -> None:
        try:
            os.unlink(os.path.join(self._keys_dir, _enc(key)))
        except FileNotFoundError:
            pass

    async def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        out: Dict[str, bytes] = {}
        for name in os.listdir(self._keys_dir):
            # skip only our own in-flight temp files (pattern from
            # _write_atomic: "<encoded-key>.tmp.<pid>.<hex6>"), not any key
            # whose decoded name happens to contain ".tmp"
            if _TMP_RE.search(name):
                continue
            key = _dec(name)
            if key.startswith(prefix):
                val = self._read_key(key)
                if val is not None:
                    out[key] = val
        return out

    async def watch(self, prefix: str) -> Watcher:
        w = Watcher()

        async def poll() -> None:
            known: Dict[str, bytes] = {}
            try:
                while True:
                    current = await self.list_prefix(prefix)
                    for k, v in sorted(current.items()):
                        if k not in known or known[k] != v:
                            w._emit(WatchEvent(EventType.PUT, k, v))
                    for k in list(known):
                        if k not in current:
                            w._emit(WatchEvent(EventType.DELETE, k, None))
                    known = current
                    await asyncio.sleep(_WATCH_POLL_S)
            except asyncio.CancelledError:
                pass

        task = asyncio.create_task(poll())
        self._watch_tasks.append(task)
        orig_cancel = w.cancel

        def cancel() -> None:
            task.cancel()
            orig_cancel()

        w.cancel = cancel  # type: ignore[method-assign]
        return w

    async def create_lease(self, ttl_s: float = DEFAULT_LEASE_TTL_S) -> Lease:
        lease_id = uuid.uuid4().hex
        self._own_leases[lease_id] = ttl_s
        rec = msgpack.packb({"hb": time.time(), "ttl": ttl_s}, use_bin_type=True)
        self._write_atomic(os.path.join(self._leases_dir, lease_id), rec)
        return Lease(lease_id, ttl_s)

    async def keep_alive(self, lease_id: str) -> bool:
        # A lease whose heartbeat already went stale must NOT be resurrected:
        # other processes may have reaped its keys, so the owner needs to see
        # the loss (return False) and re-register, matching etcd semantics.
        path = os.path.join(self._leases_dir, lease_id)
        try:
            with open(path, "rb") as f:
                prev = msgpack.unpackb(f.read(), raw=False)
        except (FileNotFoundError, ValueError):
            self._own_leases.pop(lease_id, None)
            return False
        if prev["hb"] + prev["ttl"] + self.GRACE_S <= time.time():
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            self._own_leases.pop(lease_id, None)
            return False
        ttl = self._own_leases.get(lease_id, DEFAULT_LEASE_TTL_S)
        rec = msgpack.packb({"hb": time.time(), "ttl": ttl}, use_bin_type=True)
        self._write_atomic(path, rec)
        return True

    async def revoke_lease(self, lease_id: str) -> None:
        self._own_leases.pop(lease_id, None)
        try:
            os.unlink(os.path.join(self._leases_dir, lease_id))
        except FileNotFoundError:
            pass
        # eagerly delete keys attached to this lease
        for name in os.listdir(self._keys_dir):
            path = os.path.join(self._keys_dir, name)
            try:
                with open(path, "rb") as f:
                    rec = msgpack.unpackb(f.read(), raw=False)
            except (FileNotFoundError, ValueError):
                continue
            if rec.get("lease") == lease_id:
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass

    async def close(self) -> None:
        for t in self._watch_tasks:
            t.cancel()


def make_store(kind: str, path: str = "/tmp/dtpu_store") -> KVStore:
    if kind == "mem":
        return MemKVStore()
    if kind == "file":
        return FileKVStore(path)
    if kind == "tcp":
        # networked store service (etcd-analog; push watch, shared leases):
        # path is HOST:PORT of a `python -m dynamo_tpu.runtime.discovery.netstore`
        from .netstore import TcpKVStore

        return TcpKVStore(path)
    if kind == "etcd":
        # a real etcd cluster via its v3 JSON gateway; path is the client
        # endpoint, e.g. http://etcd:2379 (discovery/etcd.py)
        from .etcd import EtcdKVStore

        return EtcdKVStore(path)
    raise ValueError(
        f"unknown store kind: {kind!r} (expected mem|file|tcp|etcd)"
    )
