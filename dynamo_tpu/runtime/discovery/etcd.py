"""etcd v3 backend for the discovery KV store.

Analog of the reference's first-class etcd layer
(lib/runtime/src/storage/kv/etcd.rs, transports/etcd/lock.rs): leases with
keepalive, key-per-instance registration, prefix watches. Speaks the etcd
gRPC-JSON gateway (the `/v3/*` HTTP API every etcd >= 3.3 serves on its
client port), so no etcd client library is needed — aiohttp is the whole
transport:

    POST /v3/kv/put | /v3/kv/range | /v3/kv/deleterange
    POST /v3/lease/grant | /v3/lease/keepalive | /v3/lease/revoke
    POST /v3/watch          (chunked stream of JSON watch responses)

Keys/values travel base64-encoded per the gateway spec. Watches follow this
store interface's snapshot-then-stream contract: one range call emits PUT
events for existing keys, then the live stream starts at the snapshot
revision + 1 so nothing is missed or duplicated.

Selected with ``DTPU_STORE=etcd`` and ``DTPU_STORE_PATH=http://host:2379``
(runtime/config.py). tests/test_etcd_store.py runs the full contract against
an in-process mock gateway — the protocol is exactly what a real etcd
serves, this image just cannot ship the binary.
"""

from __future__ import annotations

import asyncio
import base64
import json
import math
from typing import Dict, Optional

import aiohttp

from ..faults import FAULTS
from ..logging import get_logger
from ..resilience import retry_policy
from .store import (
    DEFAULT_LEASE_TTL_S,
    EventType,
    KVStore,
    Lease,
    WatchEvent,
    Watcher,
)

log = get_logger("runtime.discovery.etcd")


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _b64bytes(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def _prefix_range_end(prefix: str) -> str:
    """etcd prefix query: range_end = prefix with its last byte + 1."""
    raw = bytearray(prefix.encode())
    for i in reversed(range(len(raw))):
        if raw[i] < 0xFF:
            raw[i] += 1
            del raw[i + 1:]
            return base64.b64encode(bytes(raw)).decode()
        del raw[i]
    return base64.b64encode(b"\x00").decode()  # whole keyspace


class EtcdKVStore(KVStore):
    def __init__(self, endpoint: str):
        if not endpoint.startswith("http"):
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self._session: Optional[aiohttp.ClientSession] = None
        self._watch_tasks: list = []

    async def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=30)
            )
        return self._session

    async def _call(self, path: str, body: dict) -> dict:
        async def once() -> dict:
            await FAULTS.ainject("discovery.call")
            s = await self._http()
            try:
                async with s.post(self.endpoint + path, json=body) as r:
                    if r.status != 200:
                        err = ConnectionError(
                            f"etcd {path} -> {r.status}: {(await r.text())[:200]}"
                        )
                        if 400 <= r.status < 500 and r.status not in (408, 429):
                            # a deterministic gateway rejection (bad op,
                            # auth): still a ConnectionError for existing
                            # catchers, but marked terminal so the policy
                            # doesn't replay it
                            err.code = "invalid_request"  # type: ignore[attr-defined]
                        raise err
                    return await r.json()
            except aiohttp.ClientError as e:
                raise ConnectionError(f"etcd {path}: {e}") from e

        # every gateway op here is idempotent (put/range/deleterange/lease
        # grant+revoke), so the shared policy may replay a dropped call
        return await retry_policy(
            "discovery.call", max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
        ).acall(once)

    # ------------------------------------------------------------------- kv
    async def put(self, key: str, value: bytes, lease_id: Optional[str] = None) -> None:
        body = {"key": _b64(key), "value": _b64bytes(value)}
        if lease_id is not None:
            body["lease"] = lease_id
        await self._call("/v3/kv/put", body)

    async def get(self, key: str) -> Optional[bytes]:
        out = await self._call("/v3/kv/range", {"key": _b64(key)})
        kvs = out.get("kvs") or []
        return _unb64(kvs[0]["value"]) if kvs else None

    async def delete(self, key: str) -> None:
        await self._call("/v3/kv/deleterange", {"key": _b64(key)})

    async def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        out = await self._call("/v3/kv/range", {
            "key": _b64(prefix), "range_end": _prefix_range_end(prefix),
        })
        return {
            _unb64(kv["key"]).decode(): _unb64(kv.get("value", ""))
            for kv in (out.get("kvs") or [])
        }

    # --------------------------------------------------------------- leases
    async def create_lease(self, ttl_s: float = DEFAULT_LEASE_TTL_S) -> Lease:
        out = await self._call("/v3/lease/grant", {
            "TTL": max(1, math.ceil(ttl_s)), "ID": 0,
        })
        return Lease(id=str(out["ID"]), ttl_s=float(out.get("TTL", ttl_s)))

    async def keep_alive(self, lease_id: str) -> bool:
        # /v3/lease/keepalive is a STREAM on a real etcd: the connection
        # stays open after the first response, so read exactly one line —
        # waiting for EOF (r.text()) would hang every heartbeat until the
        # client timeout and kill the keepalive loop
        s = await self._http()
        try:
            async with s.post(
                self.endpoint + "/v3/lease/keepalive", json={"ID": lease_id},
                timeout=aiohttp.ClientTimeout(total=10),
            ) as r:
                if r.status != 200:
                    return False
                line = await r.content.readline()
        except (aiohttp.ClientError, asyncio.TimeoutError, ConnectionError):
            return False
        if not line.strip():
            return False
        first = json.loads(line)
        result = first.get("result", first)
        return int(result.get("TTL", 0) or 0) > 0

    async def revoke_lease(self, lease_id: str) -> None:
        try:
            await self._call("/v3/lease/revoke", {"ID": lease_id})
        except ConnectionError:
            pass  # already expired/revoked

    # ---------------------------------------------------------------- watch
    async def watch(self, prefix: str) -> Watcher:
        watcher = Watcher()
        # snapshot first (the store contract), remembering the revision so
        # the live stream starts exactly after it
        out = await self._call("/v3/kv/range", {
            "key": _b64(prefix), "range_end": _prefix_range_end(prefix),
        })
        for kv in out.get("kvs") or []:
            watcher._emit(WatchEvent(
                EventType.PUT, _unb64(kv["key"]).decode(),
                _unb64(kv.get("value", "")),
            ))
        rev = int(out.get("header", {}).get("revision", 0))
        task = asyncio.create_task(self._watch_stream(prefix, rev + 1, watcher))
        self._watch_tasks.append(task)
        # Watcher.cancel must also kill the stream task and its open HTTP
        # connection (the file backend sets the same convention)
        orig_cancel = watcher.cancel

        def cancel() -> None:
            task.cancel()
            orig_cancel()

        watcher.cancel = cancel  # type: ignore[method-assign]
        return watcher

    async def _watch_stream(self, prefix: str, start_rev: int, watcher: Watcher) -> None:
        """Long-lived watch with reconnect: a dropped connection (etcd
        restart, idle proxy) resumes from the last delivered revision —
        terminating the watcher on a transient error would freeze the
        client's view of discovery forever. Reconnect pacing comes from the
        shared policy (scope discovery.watch): exponential backoff with
        jitter on consecutive failures, reset once a stream delivers."""
        next_rev = start_rev
        policy = retry_policy(
            "discovery.watch", max_attempts=2, base_delay_s=0.25, max_delay_s=5.0,
        )
        prev_delay = None
        try:
            while not watcher._closed:
                try:
                    await FAULTS.ainject("discovery.watch")
                    next_rev = await self._watch_once(prefix, next_rev, watcher)
                    prev_delay = None  # the stream delivered: backoff resets
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    prev_delay = policy.next_delay(prev_delay)
                    log.warning(
                        "etcd watch for %r dropped (%s); reconnecting in %.2fs",
                        prefix, e, prev_delay,
                    )
                    await asyncio.sleep(prev_delay)
        except asyncio.CancelledError:
            pass
        finally:
            watcher.cancel()

    async def _watch_once(self, prefix: str, start_rev: int, watcher: Watcher) -> int:
        body = {"create_request": {
            "key": _b64(prefix),
            "range_end": _prefix_range_end(prefix),
            "start_revision": start_rev,
        }}
        next_rev = start_rev
        s = await self._http()
        async with s.post(
            self.endpoint + "/v3/watch", json=body,
            timeout=aiohttp.ClientTimeout(total=None),
        ) as r:
            # Frame-robust parse: the gRPC-gateway usually emits one JSON
            # object per line, but nothing in HTTP chunking guarantees a
            # frame boundary per read — an object can arrive split across
            # iter_any() chunks or concatenated with the next one on one
            # line. raw_decode consumes complete objects wherever they end;
            # an incomplete tail just waits for more bytes. The incremental
            # UTF-8 decoder keeps a multi-byte codepoint split across chunks
            # from blowing up the str conversion.
            import codecs

            udec = codecs.getincrementaldecoder("utf-8")()
            jdec = json.JSONDecoder()
            # an unparsed tail larger than any sane watch frame means the
            # body is garbage (proxy error page, corrupted stream), not a
            # split frame — raise so the watch loop reconnects instead of
            # buffering forever in silence
            max_frame = 8 * 1024 * 1024
            text = ""
            async for chunk in r.content.iter_any():
                text += udec.decode(chunk)
                idx = 0
                while True:
                    while idx < len(text) and text[idx] in " \t\r\n":
                        idx += 1
                    if idx >= len(text):
                        break
                    try:
                        msg, idx = jdec.raw_decode(text, idx)
                    except json.JSONDecodeError:
                        if text[idx] not in "{[":
                            # can't be the start of a gateway frame: garbage
                            # (e.g. a proxy's HTML error page) — reconnect
                            raise ValueError(
                                f"non-JSON watch data: {text[idx:idx + 80]!r}"
                            )
                        if len(text) - idx > max_frame:
                            raise ValueError(
                                f"unparseable watch frame ({len(text) - idx} "
                                "buffered bytes with no JSON object)"
                            )
                        break  # incomplete object: need more bytes
                    result = msg.get("result", msg)
                    for ev in result.get("events") or []:
                        kind = (
                            EventType.DELETE
                            if ev.get("type") == "DELETE" else EventType.PUT
                        )
                        kv = ev.get("kv", {})
                        key = _unb64(kv.get("key", "")).decode()
                        val = (
                            _unb64(kv["value"])
                            if kind is EventType.PUT and "value" in kv
                            else None
                        )
                        mod = int(kv.get("mod_revision", 0) or 0)
                        next_rev = max(next_rev, mod + 1)
                        watcher._emit(WatchEvent(kind, key, val))
                text = text[idx:]
        return next_rev

    async def close(self) -> None:
        for t in self._watch_tasks:
            t.cancel()
        if self._session is not None and not self._session.closed:
            await self._session.close()
