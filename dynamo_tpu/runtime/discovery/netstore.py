"""Networked discovery KV store: the etcd-analog backend.

Analog of the reference's etcd storage/discovery backend (lib/runtime/src/
storage/kv/etcd.rs + discovery/kv_store.rs). No etcd ships in this image, so
the framework carries its own store service: a ``KVStoreServer`` wrapping the
in-memory store (leases, TTL reaping, prefix watch) behind a framed-msgpack
TCP protocol, and a ``TcpKVStore`` client implementing the standard KVStore
interface. Unlike the file backend's 100ms polling watcher, watch events are
**pushed**: a mutation reaches every connected watcher in one network hop.

Protocol: every frame is ``!I``-length-prefixed msgpack. Client requests
carry ``rid`` (request id); the server answers with the same ``rid``. Watch
registration pins a server-side task that streams ``{"watch": wid, ...}``
frames interleaved with responses on the same connection.

Run the service with ``python -m dynamo_tpu.runtime.discovery.netstore`` and
point components at it with ``--store tcp --store-path HOST:PORT``.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Dict, Optional

import msgpack

from ..faults import FAULTS
from ..logging import get_logger
from ..resilience import retry_policy
from .store import (
    DEFAULT_LEASE_TTL_S,
    EventType,
    KVStore,
    Lease,
    MemKVStore,
    Watcher,
    WatchEvent,
)

log = get_logger("runtime.netstore")

_LEN = struct.Struct("!I")


def _frame(obj: dict) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    return _LEN.pack(len(body)) + body


async def _read(reader: asyncio.StreamReader) -> dict:
    raw = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(raw)
    return msgpack.unpackb(await reader.readexactly(n), raw=False)


class KVStoreServer:
    """The store service: MemKVStore state + framed TCP front."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self.host = host
        self.port = port
        self.store = MemKVStore()
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()

    async def start(self) -> str:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("kv store server on %s:%d", self.host, self.port)
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # py3.12 wait_closed() blocks until every connection handler
            # returns, and clients hold connections open — cancel them
            for t in list(self._conn_tasks):
                t.cancel()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                pass
        await self.store.close()

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        watch_tasks: Dict[int, asyncio.Task] = {}
        watchers: Dict[int, Watcher] = {}
        send_lock = asyncio.Lock()

        async def send(obj: dict) -> None:
            async with send_lock:
                writer.write(_frame(obj))
                await writer.drain()

        async def pump(wid: int, w: Watcher) -> None:
            try:
                async for ev in w:
                    await send({
                        "watch": wid,
                        "type": ev.type.value,
                        "key": ev.key,
                        "value": ev.value,
                    })
            except (ConnectionResetError, asyncio.CancelledError):
                pass

        try:
            while True:
                try:
                    req = await _read(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                rid, op = req.get("rid"), req.get("op")
                s = self.store
                try:
                    if op == "put":
                        await s.put(req["key"], req["value"], req.get("lease_id"))
                        await send({"rid": rid, "ok": True})
                    elif op == "get":
                        await send({"rid": rid, "value": await s.get(req["key"])})
                    elif op == "delete":
                        await s.delete(req["key"])
                        await send({"rid": rid, "ok": True})
                    elif op == "list":
                        await send({"rid": rid, "items": await s.list_prefix(req["prefix"])})
                    elif op == "lease_create":
                        lease = await s.create_lease(req.get("ttl", DEFAULT_LEASE_TTL_S))
                        await send({"rid": rid, "lease_id": lease.id, "ttl": lease.ttl_s})
                    elif op == "lease_keepalive":
                        await send({"rid": rid, "ok": await s.keep_alive(req["lease_id"])})
                    elif op == "lease_revoke":
                        await s.revoke_lease(req["lease_id"])
                        await send({"rid": rid, "ok": True})
                    elif op == "watch":
                        wid = req["wid"]
                        w = await s.watch(req["prefix"])
                        watchers[wid] = w
                        watch_tasks[wid] = asyncio.create_task(pump(wid, w))
                        await send({"rid": rid, "ok": True})
                    elif op == "unwatch":
                        wid = req["wid"]
                        w = watchers.pop(wid, None)
                        if w is not None:
                            w.cancel()
                        t = watch_tasks.pop(wid, None)
                        if t is not None:
                            t.cancel()
                        await send({"rid": rid, "ok": True})
                    else:
                        await send({"rid": rid, "error": f"bad op {op!r}"})
                except Exception as e:  # per-op isolation
                    log.exception("store op %r failed", op)
                    await send({"rid": rid, "error": repr(e)})
        finally:
            for w in watchers.values():
                w.cancel()
            for t in watch_tasks.values():
                t.cancel()
            if task is not None:
                self._conn_tasks.discard(task)
            writer.close()


class TcpKVStore(KVStore):
    """KVStore over one multiplexed connection to a KVStoreServer."""

    def __init__(self, address: str):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._rx_task: Optional[asyncio.Task] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._watchers: Dict[int, Watcher] = {}
        self._rid = 0
        self._wid = 0
        self._lock = asyncio.Lock()          # write ordering on the one connection
        self._connect_lock = asyncio.Lock()  # connect dedup ONLY — never held for sends

    async def _ensure(self) -> None:
        """Connect (once) OUTSIDE the send lock: when the store is down,
        every pending op used to queue single-file behind one OS-timeout-
        scale connect attempt under self._lock — a dead store serialized
        the whole discovery plane. The dedicated connect lock's entire job
        is deduplicating the dial; it guards no request traffic."""
        if self._writer is not None:
            return
        async with self._connect_lock:
            if self._writer is not None:
                return  # lost the race: the winner's connection serves us
            reader, writer = await asyncio.open_connection(  # dtpu: ignore[LOCK-ACROSS-AWAIT] — the connect lock exists to hold exactly this await; senders are not behind it
                self.host, self.port
            )
            self._reader, self._writer = reader, writer
            self._rx_task = asyncio.create_task(self._rx_loop())

    async def _rx_loop(self) -> None:
        try:
            while True:
                msg = await _read(self._reader)
                if "watch" in msg:
                    w = self._watchers.get(msg["watch"])
                    if w is not None:
                        w._emit(WatchEvent(
                            EventType(msg["type"]), msg["key"], msg["value"]
                        ))
                    continue
                fut = self._pending.pop(msg.get("rid"), None)
                if fut is not None and not fut.done():
                    if "error" in msg:
                        fut.set_exception(RuntimeError(msg["error"]))
                    else:
                        fut.set_result(msg)
        except (asyncio.IncompleteReadError, ConnectionResetError, asyncio.CancelledError):
            # sever every consumer so nobody awaits a dead connection, and
            # drop the transport so the next op reconnects (watchers do not
            # auto-resubscribe: their cancel tells consumers to re-watch)
            for fut in list(self._pending.values()):
                if not fut.done():
                    fut.set_exception(ConnectionError("kv store connection lost"))
            self._pending.clear()
            # snapshot: a watcher's wrapped cancel() pops itself from the dict
            for w in list(self._watchers.values()):
                w.cancel()
            self._watchers.clear()
            if self._writer is not None:
                try:
                    self._writer.close()
                except Exception:
                    pass
            self._reader = self._writer = None
            self._rx_task = None

    async def _call(self, obj: dict) -> dict:
        await FAULTS.ainject("discovery.call")
        await self._ensure()
        async with self._lock:
            if self._writer is None:
                # severed between _ensure and the lock: surface as the same
                # transport loss a mid-drain sever raises; _call_retry's
                # policy reconnects on the next attempt
                raise ConnectionError("kv store connection lost")
            self._rid += 1
            rid = self._rid
            obj["rid"] = rid
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[rid] = fut
            self._writer.write(_frame(obj))
            await self._writer.drain()
        return await fut

    async def _call_retry(self, obj: dict) -> dict:
        """Idempotent ops replay through the shared policy: a severed
        connection reconnects in ``_ensure`` on the next attempt instead of
        surfacing every blip to discovery consumers."""
        return await retry_policy(
            "discovery.call", max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
        ).acall(self._call, dict(obj))

    # -- KVStore interface ---------------------------------------------------
    async def put(self, key: str, value: bytes, lease_id: Optional[str] = None) -> None:
        await self._call_retry({"op": "put", "key": key, "value": value, "lease_id": lease_id})

    async def get(self, key: str) -> Optional[bytes]:
        return (await self._call_retry({"op": "get", "key": key}))["value"]

    async def delete(self, key: str) -> None:
        await self._call_retry({"op": "delete", "key": key})

    async def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        return (await self._call_retry({"op": "list", "prefix": prefix}))["items"]

    async def watch(self, prefix: str) -> Watcher:
        await self._ensure()
        async with self._lock:
            self._wid += 1
            wid = self._wid
        w = Watcher()
        orig_cancel = w.cancel

        def cancel() -> None:
            orig_cancel()
            self._watchers.pop(wid, None)
            if self._writer is not None:
                try:
                    self._writer.write(_frame({"op": "unwatch", "wid": wid, "rid": 0}))
                except ConnectionError:
                    pass

        w.cancel = cancel  # type: ignore[method-assign]
        self._watchers[wid] = w
        await self._call({"op": "watch", "prefix": prefix, "wid": wid})
        return w

    async def create_lease(self, ttl_s: float = DEFAULT_LEASE_TTL_S) -> Lease:
        resp = await self._call({"op": "lease_create", "ttl": ttl_s})
        return Lease(resp["lease_id"], resp["ttl"])

    async def keep_alive(self, lease_id: str) -> bool:
        try:
            return bool((await self._call({"op": "lease_keepalive", "lease_id": lease_id}))["ok"])
        except (ConnectionError, RuntimeError):
            return False

    async def revoke_lease(self, lease_id: str) -> None:
        await self._call({"op": "lease_revoke", "lease_id": lease_id})

    async def close(self) -> None:
        if self._rx_task is not None:
            self._rx_task.cancel()
        if self._writer is not None:
            self._writer.close()
        for w in list(self._watchers.values()):
            w.cancel()


def main() -> None:  # python -m dynamo_tpu.runtime.discovery.netstore
    import argparse
    import signal

    from ..logging import init_logging

    p = argparse.ArgumentParser("dynamo_tpu.netstore")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7460)
    args = p.parse_args()

    async def run() -> None:
        init_logging()
        server = KVStoreServer(args.host, args.port)
        addr = await server.start()
        print(f"KVSTORE_READY {addr}", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await server.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
