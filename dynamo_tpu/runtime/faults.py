"""Deterministic fault-injection plane.

A process-local registry of named fault points threaded through every
communication plane, armed from config/env so chaos tests can *provoke*
the failures the resilience layer (runtime/resilience.py) must absorb —
reproducibly, because probabilistic rules draw from a seeded schedule keyed
on the per-point call index, never on wall time.

Spec grammar (``DTPU_FAULTS``, ``;``-separated rules)::

    point:action[=value][@qualifier[@qualifier...]]

    actions     fail          raise FaultInjected (typed application error)
                drop          raise InjectedDrop (a ConnectionError: looks
                              like transport loss to retry/migration)
                delay=S       sleep S seconds, then proceed
                hang=S        alias of delay for long stalls (watchdog tests)
                corrupt       flip payload bytes (sites that call mangle())
    qualifiers  @N            fire on the Nth call only (1-based)
                @N+           fire on the Nth call and every call after
                @p=0.3        fire each call with probability 0.3
                @seed=7       seed the probabilistic schedule (implies
                              p=0.5 when @p is absent); same seed => same
                              schedule
                (none)        fire on every call

Examples::

    DTPU_FAULTS="transfer.pull:drop@2;etcd.watch:delay=0.5@seed=7"
    DTPU_FAULTS="request_plane.send:drop@p=0.25@seed=11"

Well-known fault points (the catalog below documents the wired sites; the
registry accepts any name, so tests can add their own):
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from .logging import get_logger

log = get_logger("runtime.faults")

ENV_FAULTS = "DTPU_FAULTS"

# catalog of wired fault points (docs/operations.md "Failure handling")
FAULT_POINTS = (
    "request_plane.send",        # tcp/http client, before the request goes out
    "request_plane.connect",     # tcp/http client connection establishment
    "event_plane.publish",       # zmq + inproc event planes
    "discovery.call",            # etcd / netstore KV operations
    "discovery.lease_keepalive", # runtime keepalive heartbeat
    "discovery.watch",           # etcd watch stream (per reconnect attempt)
    "transfer.pull",             # KV transfer client fetch
    "transfer.stream_window",    # streamed fetch, per block window (client)
    "transfer.native_fetch",     # native (C++ agent) bulk fetch
    "engine.step",               # engine step loop (crash/watchdog drills)
    "controller.spawn",          # deploy controller process spawn
    "drain.notice",              # reclaim notice delivery (engine/drain.py)
    "checkpoint.write",          # per sealed-block checkpoint file write
    "checkpoint.manifest",       # atomic manifest commit (pre-rename)
    "restore.read",              # checkpoint manifest/block read on restore
    "directory.publish",         # global KV directory advertisement write
    "directory.lookup",          # global KV directory hash lookup
    "fetch.peer_tier",           # peer G2/G3 tier fetch (client side)
)

ACTIONS = ("fail", "drop", "delay", "hang", "corrupt")


class FaultInjected(RuntimeError):
    """A deliberately injected application-level failure."""

    code = "fault_injected"


class InjectedDrop(ConnectionError):
    """A deliberately injected transport loss (retryable by policy)."""

    code = "fault_drop"


@dataclasses.dataclass
class FaultRule:
    point: str
    action: str
    value: Optional[float] = None   # seconds for delay/hang
    nth: Optional[int] = None       # 1-based call index
    from_nth: bool = False
    prob: Optional[float] = None
    seed: Optional[int] = None

    def __post_init__(self) -> None:
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}")
        if self.action in ("delay", "hang") and self.value is None:
            raise ValueError(f"{self.action} needs a value, e.g. delay=0.5")
        if self.seed is not None and self.prob is None:
            self.prob = 0.5
        self._rng = random.Random(self.seed)
        # memoized per-call decisions: fires_at(i) is a pure function of
        # (rule, seed, i) regardless of evaluation order
        self._decisions: List[bool] = []

    def fires_at(self, i: int) -> bool:
        """Does this rule fire on the point's ``i``-th call (1-based)?"""
        if self.nth is not None:
            return i >= self.nth if self.from_nth else i == self.nth
        if self.prob is not None:
            while len(self._decisions) < i:
                self._decisions.append(self._rng.random() < self.prob)
            return self._decisions[i - 1]
        return True


def parse_faults(spec: str) -> List[FaultRule]:
    rules: List[FaultRule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        point, sep, rest = part.partition(":")
        if not sep or not point or not rest:
            raise ValueError(f"bad fault rule {part!r} (want point:action[...])")
        fields = rest.split("@")
        action, vsep, raw_val = fields[0].partition("=")
        value = None
        if vsep:
            try:
                value = float(raw_val)
            except ValueError:
                raise ValueError(f"bad fault value in {part!r}") from None
        nth = None
        from_nth = False
        prob = None
        seed = None
        for q in fields[1:]:
            q = q.strip()
            if q.endswith("+") and q[:-1].isdigit():
                nth, from_nth = int(q[:-1]), True
            elif q.isdigit():
                nth = int(q)
            elif q.startswith("p="):
                prob = float(q[2:])
            elif q.startswith("seed="):
                seed = int(q[5:])
            else:
                raise ValueError(f"bad fault qualifier {q!r} in {part!r}")
        rules.append(FaultRule(
            point=point.strip(), action=action.strip(), value=value,
            nth=nth, from_nth=from_nth, prob=prob, seed=seed,
        ))
    return rules


class FaultRegistry:
    """Armed fault rules + per-point call counters + fired-event log.

    The unarmed fast path is one falsy-dict check, so instrumented hot paths
    cost nothing in production. ``fired`` records ``(point, action, call_n)``
    for every injection — chaos tests assert two runs with the same seeds
    produce identical logs.
    """

    def __init__(self) -> None:
        self._rules: Dict[str, List[FaultRule]] = {}
        self._calls: Dict[str, int] = {}
        self.fired: List[Tuple[str, str, int]] = []
        self._lock = threading.Lock()

    # -- arming --------------------------------------------------------------
    @property
    def armed(self) -> bool:
        return bool(self._rules)

    def arm(self, spec: str) -> None:
        for rule in parse_faults(spec):
            self.arm_rule(rule)

    def arm_rule(self, rule: FaultRule) -> None:
        with self._lock:
            self._rules.setdefault(rule.point, []).append(rule)
        log.warning("fault armed: %s:%s", rule.point, rule.action)

    def disarm(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._rules.clear()
                self._calls.clear()
                self.fired = []
            else:
                self._rules.pop(point, None)
                self._calls.pop(point, None)

    def calls(self, point: str) -> int:
        return self._calls.get(point, 0)

    def plan(self, point: str, n_calls: int) -> List[Tuple[int, str]]:
        """Preview which of the next ``n_calls`` calls would fire, WITHOUT
        consuming the schedule (fresh rule clones are interrogated). Lets
        tests assert determinism against the live ``fired`` log."""
        out: List[Tuple[int, str]] = []
        for rule in self._rules.get(point, ()):  # same arming order
            clone = dataclasses.replace(rule)
            for i in range(1, n_calls + 1):
                if clone.fires_at(i):
                    out.append((i, rule.action))
        out.sort()
        return out

    # -- firing --------------------------------------------------------------
    def _fire(self, point: str, corrupt_pass: bool) -> List[FaultRule]:
        rules = self._rules.get(point)
        if not rules:
            return []
        counter = point + "#corrupt" if corrupt_pass else point
        with self._lock:
            i = self._calls.get(counter, 0) + 1
            self._calls[counter] = i
            hits = [
                r for r in rules
                if (r.action == "corrupt") == corrupt_pass and r.fires_at(i)
            ]
            for r in hits:
                self.fired.append((point, r.action, i))
        for r in hits:
            log.warning("fault fired: %s:%s (call %d)", point, r.action, i)
        return hits

    def _raise_for(self, rule: FaultRule, point: str) -> None:
        if rule.action == "drop":
            raise InjectedDrop(f"injected drop at {point}")
        if rule.action == "fail":
            raise FaultInjected(f"injected failure at {point}")

    def inject(self, point: str) -> None:
        """Sync fault point: delay/hang block the thread; drop/fail raise."""
        if not self._rules:
            return
        for rule in self._fire(point, corrupt_pass=False):
            if rule.action in ("delay", "hang"):
                time.sleep(float(rule.value))
            else:
                self._raise_for(rule, point)

    async def ainject(self, point: str) -> None:
        """Async fault point: delay/hang await; drop/fail raise."""
        if not self._rules:
            return
        for rule in self._fire(point, corrupt_pass=False):
            if rule.action in ("delay", "hang"):
                await asyncio.sleep(float(rule.value))
            else:
                self._raise_for(rule, point)

    def mangle(self, point: str, payload: bytes) -> bytes:
        """Apply armed ``corrupt`` rules to a payload (separate call counter,
        suffix ``#corrupt``, so a site may call inject() AND mangle())."""
        if not self._rules:
            return payload
        for _rule in self._fire(point, corrupt_pass=True):
            if payload:
                payload = bytes([payload[0] ^ 0xFF]) + payload[1:]
        return payload


FAULTS = FaultRegistry()


def reload_from_env() -> None:
    """(Re)arm the process registry from ``DTPU_FAULTS``; tests use this
    after mutating the env. A bad spec logs and leaves the registry clean —
    a typo must not take the worker down before the chaos drill starts."""
    FAULTS.disarm()
    spec = os.environ.get(ENV_FAULTS)
    if not spec:
        return
    try:
        FAULTS.arm(spec)
    except ValueError as e:
        log.error("ignoring bad %s=%r: %s", ENV_FAULTS, spec, e)


reload_from_env()
