"""Hierarchical task management: tracked spawn, policies, graceful drain.

Analog of the reference's TaskTracker (lib/runtime/src/utils/tasks/
tracker.rs — scheduler + error-policy + hierarchical cancellation) and its
critical-task escalation (tasks/critical.rs), in asyncio idiom:

- ``TaskTracker.spawn(coro)`` runs a coroutine under a scheduling policy
  (unlimited or a concurrency-limited semaphore) and an error policy;
- error policies: ``FAIL`` (log + record), ``SHUTDOWN`` (a failure cancels
  the whole tracker tree — the critical-task semantic), or a custom
  ``on_error(exc, task_id) -> "fail" | "shutdown" | "retry"`` callable with
  bounded retries;
- ``child()`` trackers inherit cancellation from the parent (shutting down a
  parent drains the entire subtree);
- ``graceful_shutdown(timeout)`` stops intake, waits for in-flight work,
  then cancels stragglers — the drain the reference performs on worker
  shutdown.
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import uuid
from typing import Any, Awaitable, Callable, Dict, List, Optional

from .logging import get_logger

log = get_logger("runtime.tasks")


# fire-and-forget background tasks: the event loop holds tasks only by WEAK
# reference, so a task whose handle is discarded can be garbage-collected
# mid-flight and silently die. spawn_bg pins the task until it completes
# (tools/lint.py DROPPED-TASK enforces its use over bare ensure_future).
_BG_TASKS: set = set()


def _bg_done(task: "asyncio.Task") -> None:
    _BG_TASKS.discard(task)
    if not task.cancelled() and task.exception() is not None:
        log.error("background task failed: %r", task.exception())


def spawn_bg(coro) -> "asyncio.Task":
    task = asyncio.ensure_future(coro)
    _BG_TASKS.add(task)
    task.add_done_callback(_bg_done)
    return task


class ErrorPolicy(enum.Enum):
    FAIL = "fail"          # record + continue
    SHUTDOWN = "shutdown"  # any failure cancels the tracker tree


@dataclasses.dataclass
class TaskMetrics:
    issued: int = 0
    started: int = 0
    ok: int = 0
    failed: int = 0
    cancelled: int = 0
    rejected: int = 0

    @property
    def active(self) -> int:
        return self.started - self.ok - self.failed - self.cancelled


class TaskHandle:
    """Await-able handle with cancellation (tracker.rs TaskHandle analog)."""

    def __init__(self, task_id: str, task: asyncio.Task):
        self.task_id = task_id
        self._task = task

    def cancel(self) -> None:
        self._task.cancel()

    @property
    def done(self) -> bool:
        return self._task.done()

    def __await__(self):
        return self._task.__await__()


class TaskTracker:
    def __init__(
        self,
        max_concurrency: Optional[int] = None,
        error_policy: Any = ErrorPolicy.FAIL,
        max_retries: int = 0,
        name: str = "root",
        parent: Optional["TaskTracker"] = None,
    ):
        self.name = name
        self.parent = parent
        self.error_policy = error_policy
        self.max_retries = max_retries
        self.metrics = TaskMetrics()
        self._sem = (
            asyncio.Semaphore(max_concurrency) if max_concurrency else None
        )
        self._tasks: Dict[str, asyncio.Task] = {}
        self._children: List["TaskTracker"] = []
        self._closed = False
        self.last_error: Optional[BaseException] = None

    # -- hierarchy -----------------------------------------------------------
    def child(self, name: str, **kw) -> "TaskTracker":
        c = TaskTracker(name=f"{self.name}/{name}", parent=self, **kw)
        self._children.append(c)
        return c

    @property
    def closed(self) -> bool:
        return self._closed or (self.parent is not None and self.parent.closed)

    # -- spawning ------------------------------------------------------------
    def spawn(
        self,
        fn: Callable[[], Awaitable[Any]],
        name: Optional[str] = None,
    ) -> TaskHandle:
        """Run ``fn()`` (a coroutine factory, so retries can re-invoke it)
        under the tracker's policies. Raises RuntimeError once closed."""
        if self.closed:
            self.metrics.rejected += 1
            raise RuntimeError(f"tracker {self.name} is shut down")
        task_id = name or uuid.uuid4().hex[:8]
        self.metrics.issued += 1
        task = asyncio.create_task(self._run(task_id, fn))
        self._tasks[task_id] = task

        def _cleanup(t: asyncio.Task) -> None:
            # only evict OUR entry: a later spawn under the same name must
            # not lose tracking when the earlier task finishes
            if self._tasks.get(task_id) is t:
                self._tasks.pop(task_id, None)

        task.add_done_callback(_cleanup)
        return TaskHandle(task_id, task)

    async def _run(self, task_id: str, fn: Callable[[], Awaitable[Any]]) -> Any:
        attempt = 0
        while True:
            if self._sem is not None:
                await self._sem.acquire()
            self.metrics.started += 1
            try:
                result = await fn()
                self.metrics.ok += 1
                return result
            except asyncio.CancelledError:
                self.metrics.cancelled += 1
                raise
            except Exception as e:
                self.metrics.failed += 1
                self.last_error = e
                decision = self._decide(e, task_id)
                if decision == "retry" and attempt < self.max_retries:
                    attempt += 1
                    log.warning(
                        "task %s/%s failed (%r); retry %d/%d",
                        self.name, task_id, e, attempt, self.max_retries,
                    )
                    continue
                if decision == "shutdown":
                    log.error(
                        "critical task %s/%s failed (%r); shutting tracker down",
                        self.name, task_id, e,
                    )
                    self.shutdown()
                else:
                    log.exception("task %s/%s failed", self.name, task_id)
                raise
            finally:
                if self._sem is not None:
                    self._sem.release()

    def _decide(self, exc: Exception, task_id: str) -> str:
        if callable(self.error_policy):
            try:
                return self.error_policy(exc, task_id)
            except Exception:
                log.exception("error policy itself failed; treating as FAIL")
                return "fail"
        if self.error_policy is ErrorPolicy.SHUTDOWN:
            return "shutdown"
        return "retry" if self.max_retries else "fail"

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self) -> None:
        """Immediate: cancel everything in this tracker and its subtree."""
        self._closed = True
        for t in list(self._tasks.values()):
            t.cancel()
        for c in self._children:
            c.shutdown()

    async def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for in-flight tasks (and children). True if all finished."""
        tasks = list(self._tasks.values())
        done_all = True
        if tasks:
            done, pending = await asyncio.wait(
                tasks, timeout=timeout,
                return_when=asyncio.ALL_COMPLETED,
            )
            done_all = not pending
        for c in self._children:
            done_all = await c.join(timeout) and done_all
        return done_all

    async def graceful_shutdown(self, timeout: float = 10.0) -> bool:
        """Drain: stop intake, wait up to ``timeout``, then cancel stragglers.
        Returns True when everything finished within the deadline."""
        self._closed = True
        for c in self._children:
            c._closed = True
        finished = await self.join(timeout)
        if not finished:
            log.warning(
                "tracker %s drain timed out after %.1fs; cancelling %d tasks",
                self.name, timeout, self.metrics.active,
            )
            self.shutdown()
            await self.join(2.0)
        return finished
