"""Health subsystem: canary checks + per-component system status server.

Analogs of the reference's canary health checks (lib/runtime/src/
health_check.rs — synthetic probes through the real serving path, not just
process liveness) and the system status server
(lib/runtime/src/system_status_server.rs:159-215 — /health /live /metrics
/metadata on a side port for every component, not only the HTTP frontend).

The canary pings a worker's own served endpoints over the actual TCP request
plane (connect + codec + server loop), so a wedged event loop or dead socket
fails the probe even while the process is alive. Consecutive failures flip
the subsystem unhealthy and fire a callback (deregister, shed, restart —
caller's choice).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Awaitable, Callable, Dict, Optional

from aiohttp import web

from . import metrics as M
from .config import ENV_CANARY_WAIT_TIME, ENV_SYSTEM_HOST, env_float, env_str
from .logging import get_logger
from .request_plane.tcp import TcpClient
from .tasks import spawn_bg

log = get_logger("runtime.health")


class HealthState:
    """Aggregated health of named subsystems (endpoints, engine, planes)."""

    def __init__(self):
        self._subsystems: Dict[str, bool] = {}
        self._detail: Dict[str, str] = {}

    def set(self, name: str, healthy: bool, detail: str = "") -> None:
        self._subsystems[name] = healthy
        self._detail[name] = detail

    def remove(self, name: str) -> None:
        self._subsystems.pop(name, None)
        self._detail.pop(name, None)

    @property
    def healthy(self) -> bool:
        return all(self._subsystems.values()) if self._subsystems else True

    def snapshot(self) -> Dict[str, Any]:
        return {
            "status": "healthy" if self.healthy else "unhealthy",
            "subsystems": {
                name: {"healthy": ok, "detail": self._detail.get(name, "")}
                for name, ok in self._subsystems.items()
            },
        }


class EndpointCanary:
    """Periodic request-plane pings of served endpoints.

    targets: name -> address. After ``fail_threshold`` consecutive failures a
    target is marked unhealthy in ``state`` and ``on_unhealthy(name)`` fires
    once per downtime episode; a later success marks it healthy again."""

    def __init__(
        self,
        targets: Dict[str, str],
        state: Optional[HealthState] = None,
        interval_s: Optional[float] = None,
        timeout_s: float = 2.0,
        fail_threshold: int = 3,
        on_unhealthy: Optional[Callable[[str], Awaitable[None]]] = None,
    ):
        self.targets = dict(targets)
        self.state = state or HealthState()
        # DTPU_CANARY_WAIT_TIME (reference canary_wait_time) paces the probe
        # loop when the caller leaves it open
        self.interval_s = (
            env_float(ENV_CANARY_WAIT_TIME, 1.0) if interval_s is None else interval_s
        )
        self.timeout_s = timeout_s
        self.fail_threshold = fail_threshold
        self.on_unhealthy = on_unhealthy
        self.last_rtt: Dict[str, float] = {}
        self._fails: Dict[str, int] = {}
        self._down: set = set()
        self._client = TcpClient()
        self._http_client = None  # lazy, for http:// request-plane addresses
        self._task: Optional[asyncio.Task] = None
        for name in self.targets:
            self.state.set(name, True, "not probed yet")

    def _client_for(self, address: str):
        if address.startswith("http"):
            if self._http_client is None:
                from .request_plane.http import HttpClient

                self._http_client = HttpClient()
            return self._http_client
        return self._client

    async def probe_once(self) -> None:
        for name, address in list(self.targets.items()):
            try:
                rtt = await self._client_for(address).ping(
                    address, timeout=self.timeout_s
                )
                self.last_rtt[name] = rtt
                self._fails[name] = 0
                self._down.discard(name)
                self.state.set(name, True, f"rtt={rtt*1000:.1f}ms")
            except Exception as e:
                n = self._fails.get(name, 0) + 1
                self._fails[name] = n
                if n >= self.fail_threshold:
                    self.state.set(name, False, f"{n} consecutive failures: {e}")
                    if name not in self._down:
                        self._down.add(name)
                        log.warning("canary: endpoint %s unhealthy (%s)", name, e)
                        if self.on_unhealthy is not None:
                            try:
                                await self.on_unhealthy(name)
                            except Exception:
                                # the callback (deregister, shed, restart)
                                # tends to hit the same dead infrastructure
                                # the canary just detected; its failure must
                                # not kill the probe loop — the canary is
                                # most needed exactly then
                                log.exception(
                                    "canary: on_unhealthy(%s) failed", name
                                )

    def start(self) -> "EndpointCanary":
        async def loop() -> None:
            try:
                while True:
                    await self.probe_once()
                    await asyncio.sleep(self.interval_s)
            except asyncio.CancelledError:
                pass

        # spawn_bg: a canary that dies from an unexpected error must log,
        # not silently stop probing while /health keeps reporting stale state
        self._task = spawn_bg(loop())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        await self._client.close()
        if self._http_client is not None:
            await self._http_client.close()


class StatusServer:
    """Side-port HTTP server exposing component health and metrics.

    Routes (reference system_status_server.rs:159-215):
      /health    aggregated HealthState (+ canary RTTs), 503 when unhealthy
      /live      process liveness (always 200 while serving)
      /metrics   Prometheus exposition from the runtime registry
      /metadata  caller-provided component metadata (model, config, snapshot)
      /v1/loras  loaded LoRA adapters (system_status_server.rs:196-215)
      /debug/requests  flight-recorder timelines (runtime/flight_recorder.py);
                 ``?id=<request_id>`` returns one timeline, 404 if evicted
      /debug/slo  per-(model, sla_class) attainment/burn-rate/goodput ledger
                 (runtime/slo.py SloAccountant; the worker-side view fed
                 from engine milestone timestamps)
      POST /drain  planned-reclaim notice (engine/drain.py DrainCoordinator;
                 docs/operations.md §13): body ``{"deadline_s": 30}`` —
                 flips discovery to `draining`, evacuates/checkpoints, 409
                 when no drain handler is wired
    """

    def __init__(
        self,
        state: HealthState,
        metrics_scope: Optional[M.MetricsScope] = None,
        metadata_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        pre_expose: Optional[Callable[[], None]] = None,
        host: Optional[str] = None,
        port: int = 0,
        loras_fn: Optional[Callable[[], list]] = None,
        flight_recorder=None,
        drain_fn: Optional[Callable[[Optional[float]], Awaitable[Dict[str, Any]]]] = None,
    ):
        self.state = state
        self.metrics = metrics_scope
        self.metadata_fn = metadata_fn
        self.loras_fn = loras_fn
        self.drain_fn = drain_fn
        self.pre_expose = pre_expose  # refresh gauges right before scraping
        # explicit host wins; DTPU_SYSTEM_HOST configures what callers left open
        self.host = host if host is not None else env_str(ENV_SYSTEM_HOST, "0.0.0.0")
        self.port = port
        # None = the process-global recorder (workers get /debug/requests
        # without wiring); tests pass their own
        self._flight_recorder = flight_recorder
        self.started_at = time.time()
        self._runner: Optional[web.AppRunner] = None
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/metadata", self._metadata)
        app.router.add_get("/v1/loras", self._loras)
        app.router.add_get("/debug/requests", self._debug_requests)
        app.router.add_get("/debug/slo", self._debug_slo)
        app.router.add_post("/drain", self._drain)
        self.app = app

    async def _health(self, request: web.Request) -> web.Response:
        snap = self.state.snapshot()
        return web.json_response(snap, status=200 if self.state.healthy else 503)

    async def _live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live", "uptime_s": time.time() - self.started_at})

    async def _metrics(self, request: web.Request) -> web.Response:
        if self.pre_expose is not None:
            try:
                self.pre_expose()
            except Exception:
                # stale gauges beat a failed scrape
                log.exception("metrics pre_expose hook failed")
        body = self.metrics.expose() if self.metrics is not None else b""
        return web.Response(body=body, content_type="text/plain")

    async def _metadata(self, request: web.Request) -> web.Response:
        meta = self.metadata_fn() if self.metadata_fn is not None else {}
        return web.json_response(meta)

    async def _loras(self, request: web.Request) -> web.Response:
        names = self.loras_fn() if self.loras_fn is not None else []
        return web.json_response({"data": [{"id": n} for n in names]})

    async def _debug_requests(self, request: web.Request) -> web.Response:
        from .flight_recorder import debug_requests_payload, get_flight_recorder

        rec = self._flight_recorder or get_flight_recorder()
        status, payload = debug_requests_payload(
            rec, request.query.get("id"), request.query.get("limit")
        )
        return web.json_response(payload, status=status)

    async def _debug_slo(self, request: web.Request) -> web.Response:
        from .slo import debug_slo_payload, get_slo_accountant

        return web.json_response(debug_slo_payload(get_slo_accountant()))

    async def _drain(self, request: web.Request) -> web.Response:
        if self.drain_fn is None:
            return web.json_response(
                {"error": "no drain handler on this component"}, status=409
            )
        deadline_s: Optional[float] = None
        try:
            body = await request.json()
        except Exception:
            body = {}
        raw = body.get("deadline_s", request.query.get("deadline_s"))
        if raw is not None:
            try:
                deadline_s = float(raw)
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": f"bad deadline_s {raw!r}"}, status=400
                )
        summary = await self.drain_fn(deadline_s)
        return web.json_response(summary)

    async def start(self) -> str:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("status server on %s:%d", self.host, self.port)
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
