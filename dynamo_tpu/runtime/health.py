"""Health subsystem: canary checks, degradation detectors, status server.

Analogs of the reference's canary health checks (lib/runtime/src/
health_check.rs — synthetic probes through the real serving path, not just
process liveness) and the system status server
(lib/runtime/src/system_status_server.rs:159-215 — /health /live /metrics
/metadata on a side port for every component, not only the HTTP frontend).

The canary pings a worker's own served endpoints over the actual TCP request
plane (connect + codec + server loop), so a wedged event loop or dead socket
fails the probe even while the process is alive. Consecutive failures flip
the subsystem unhealthy and fire a callback (deregister, shed, restart —
caller's choice).

The degradation detectors (:class:`HealthMonitor`) compare live signals
against expectations and emit typed, rate-limited :class:`HealthEvent`\\ s:

- ``cost_model_drift`` — measured step seconds vs the ``ops/costs.py``
  analytic prediction for the same shapes (the deterministic byte models
  auditing the live path);
- ``wire_collapse`` — a wire's bandwidth EWMA collapsing against the
  detector's own long-horizon reference of that same wire;
- ``hitrate_drop`` — radix/global-KV hit rate falling far below its own
  baseline;
- ``burn_rate_accel`` — a class's short-window error-budget burn running
  far ahead of its long-window burn.

Every detector runs through one hysteresis + rate-limit core: N consecutive
over-threshold observations trip it (no single-sample flaps), M consecutive
healthy observations clear it, and per-(detector, subject) emissions are
spaced at least ``DTPU_HEALTH_MIN_INTERVAL_S`` apart. The monitor is
clock-injectable, so the fleet simulator drives the production detectors on
its virtual clock and the `degradation-localization` scenario's invariants
assert on this exact code path.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional

from aiohttp import web

from . import metrics as M
from .config import (
    ENV_CANARY_WAIT_TIME,
    ENV_HEALTH_DRIFT_RATIO,
    ENV_HEALTH_MIN_INTERVAL_S,
    ENV_SYSTEM_HOST,
    env_float,
    env_str,
)
from .logging import get_logger
from .request_plane.tcp import TcpClient
from .tasks import spawn_bg

log = get_logger("runtime.health")


class HealthState:
    """Aggregated health of named subsystems (endpoints, engine, planes)."""

    def __init__(self):
        self._subsystems: Dict[str, bool] = {}
        self._detail: Dict[str, str] = {}

    def set(self, name: str, healthy: bool, detail: str = "") -> None:
        self._subsystems[name] = healthy
        self._detail[name] = detail

    def remove(self, name: str) -> None:
        self._subsystems.pop(name, None)
        self._detail.pop(name, None)

    @property
    def healthy(self) -> bool:
        return all(self._subsystems.values()) if self._subsystems else True

    def snapshot(self) -> Dict[str, Any]:
        return {
            "status": "healthy" if self.healthy else "unhealthy",
            "subsystems": {
                name: {"healthy": ok, "detail": self._detail.get(name, "")}
                for name, ok in self._subsystems.items()
            },
        }


class EndpointCanary:
    """Periodic request-plane pings of served endpoints.

    targets: name -> address. After ``fail_threshold`` consecutive failures a
    target is marked unhealthy in ``state`` and ``on_unhealthy(name)`` fires
    once per downtime episode; a later success marks it healthy again."""

    def __init__(
        self,
        targets: Dict[str, str],
        state: Optional[HealthState] = None,
        interval_s: Optional[float] = None,
        timeout_s: float = 2.0,
        fail_threshold: int = 3,
        on_unhealthy: Optional[Callable[[str], Awaitable[None]]] = None,
    ):
        self.targets = dict(targets)
        self.state = state or HealthState()
        # DTPU_CANARY_WAIT_TIME (reference canary_wait_time) paces the probe
        # loop when the caller leaves it open
        self.interval_s = (
            env_float(ENV_CANARY_WAIT_TIME, 1.0) if interval_s is None else interval_s
        )
        self.timeout_s = timeout_s
        self.fail_threshold = fail_threshold
        self.on_unhealthy = on_unhealthy
        self.last_rtt: Dict[str, float] = {}
        self._fails: Dict[str, int] = {}
        self._down: set = set()
        self._client = TcpClient()
        self._http_client = None  # lazy, for http:// request-plane addresses
        self._task: Optional[asyncio.Task] = None
        for name in self.targets:
            self.state.set(name, True, "not probed yet")

    def _client_for(self, address: str):
        if address.startswith("http"):
            if self._http_client is None:
                from .request_plane.http import HttpClient

                self._http_client = HttpClient()
            return self._http_client
        return self._client

    async def probe_once(self) -> None:
        for name, address in list(self.targets.items()):
            try:
                rtt = await self._client_for(address).ping(
                    address, timeout=self.timeout_s
                )
                self.last_rtt[name] = rtt
                self._fails[name] = 0
                self._down.discard(name)
                self.state.set(name, True, f"rtt={rtt*1000:.1f}ms")
            except Exception as e:
                n = self._fails.get(name, 0) + 1
                self._fails[name] = n
                if n >= self.fail_threshold:
                    self.state.set(name, False, f"{n} consecutive failures: {e}")
                    if name not in self._down:
                        self._down.add(name)
                        log.warning("canary: endpoint %s unhealthy (%s)", name, e)
                        if self.on_unhealthy is not None:
                            try:
                                await self.on_unhealthy(name)
                            except Exception:
                                # the callback (deregister, shed, restart)
                                # tends to hit the same dead infrastructure
                                # the canary just detected; its failure must
                                # not kill the probe loop — the canary is
                                # most needed exactly then
                                log.exception(
                                    "canary: on_unhealthy(%s) failed", name
                                )

    def start(self) -> "EndpointCanary":
        async def loop() -> None:
            try:
                while True:
                    await self.probe_once()
                    await asyncio.sleep(self.interval_s)
            except asyncio.CancelledError:
                pass

        # spawn_bg: a canary that dies from an unexpected error must log,
        # not silently stop probing while /health keeps reporting stale state
        self._task = spawn_bg(loop())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        await self._client.close()
        if self._http_client is not None:
            await self._http_client.close()


# ---------------------------------------------------------------------------
# degradation detectors
# ---------------------------------------------------------------------------

DEFAULT_DRIFT_RATIO = 2.0        # measured/predicted step time trip point
DEFAULT_COLLAPSE_FRAC = 0.3      # bandwidth below this fraction of reference
DEFAULT_HITRATE_DROP = 0.5       # hit rate below this fraction of baseline
DEFAULT_BURN_ACCEL = 4.0         # short-window burn over long-window burn
DEFAULT_MIN_INTERVAL_S = 30.0    # per-(detector, subject) emission spacing
_TRIP_N = 3                      # consecutive bad observations to trip
_CLEAR_N = 3                     # consecutive good observations to clear
_CLEAR_SLACK = 0.8               # clear threshold = slack * trip threshold
_EVENTS_RETAINED = 256
_REFERENCE_ALPHA = 0.02          # long-horizon reference EWMA
_MIN_REFERENCE_OBS = 10          # observations before a detector arms


@dataclasses.dataclass
class HealthEvent:
    """One typed degradation event (what fired, on what, how far off)."""

    detector: str     # cost_model_drift | wire_collapse | hitrate_drop | ...
    subject: str      # "worker/3", "wire/inline", "class/interactive", ...
    kind: str         # "degraded" | "recovered"
    value: float      # the measured signal
    expected: float   # the reference it was compared against
    ratio: float      # value/expected (drift) or value/reference (others)
    t: float          # monitor-clock seconds
    detail: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "detector": self.detector,
            "subject": self.subject,
            "kind": self.kind,
            "value": round(self.value, 6),
            "expected": round(self.expected, 6),
            "ratio": round(self.ratio, 4),
            "t": round(self.t, 3),
            "detail": self.detail,
        }


class _SubjectState:
    """Hysteresis + rate-limit core shared by every detector: trip after
    ``_TRIP_N`` consecutive over-threshold observations, clear after
    ``_CLEAR_N`` consecutive observations under ``_CLEAR_SLACK`` of the
    trip threshold — the gap between the two thresholds is the no-flap
    band. Emissions per subject are spaced ``min_interval_s`` apart."""

    __slots__ = ("bad", "good", "tripped", "last_emit", "reference", "obs")

    def __init__(self) -> None:
        self.bad = 0
        self.good = 0
        self.tripped = False
        self.last_emit = float("-inf")
        self.reference: Optional[float] = None
        self.obs = 0


class HealthSubscription:
    """Handle for one subscriber callback; ``close()`` detaches it
    (RESOURCE-LEAK: health-subscription)."""

    def __init__(self, monitor: "HealthMonitor",
                 callback: Callable[[HealthEvent], None]):
        self._monitor = monitor
        self._callback = callback

    def close(self) -> None:
        self._monitor._subscribers.discard(self)


class HealthMonitor:
    """Clock-injectable degradation detectors over live serving signals.

    One monitor per component; producers call the ``observe_*`` feeds from
    wherever the signal lives (the step-stats hook, the bandwidth
    estimator's consumer, the SLO accountant reader). Emissions go to the
    bounded ``recent`` ring (the ``/debug/worker`` payload), the flight
    recorder under a synthetic ``health:<detector>`` timeline, the
    ``dtpu_health_events_total`` counter, and any subscribers (the worker
    main publishes them onto the event plane).
    """

    def __init__(
        self,
        clock: Optional[Callable[[], float]] = None,
        min_interval_s: Optional[float] = None,
        drift_ratio: Optional[float] = None,
        collapse_frac: float = DEFAULT_COLLAPSE_FRAC,
        hitrate_drop: float = DEFAULT_HITRATE_DROP,
        burn_accel: float = DEFAULT_BURN_ACCEL,
        metrics: Optional[M.MetricsScope] = None,
        flight_recorder=None,
    ):
        self._clock = clock if clock is not None else time.monotonic
        self.min_interval_s = (
            env_float(ENV_HEALTH_MIN_INTERVAL_S, DEFAULT_MIN_INTERVAL_S)
            if min_interval_s is None else min_interval_s
        )
        self.drift_ratio = (
            env_float(ENV_HEALTH_DRIFT_RATIO, DEFAULT_DRIFT_RATIO)
            if drift_ratio is None else drift_ratio
        )
        self.collapse_frac = collapse_frac
        self.hitrate_drop = hitrate_drop
        self.burn_accel = burn_accel
        self._flight = flight_recorder
        self._states: Dict[tuple, _SubjectState] = {}
        self._subscribers: set = set()
        self.recent: "collections.deque[HealthEvent]" = collections.deque(
            maxlen=_EVENTS_RETAINED
        )
        self.counts: Dict[str, int] = {}
        self._events_c = None
        if metrics is not None:
            self.bind_metrics(metrics)

    def bind_metrics(self, scope: M.MetricsScope) -> None:
        self._events_c = scope.counter(
            M.HEALTH_EVENTS_TOTAL,
            "degradation-detector events",
            extra_labels=("detector", "kind"),
        )

    def subscribe(
        self, callback: Callable[[HealthEvent], None]
    ) -> HealthSubscription:
        sub = HealthSubscription(self, callback)
        self._subscribers.add(sub)
        return sub

    # -- detector feeds ------------------------------------------------------
    def observe_step(
        self, subject: str, measured_s: float, predicted_s: float,
        phase: str = "decode",
    ) -> Optional[HealthEvent]:
        """Cost-model drift: host-measured step time vs the ops/costs.py
        analytic prediction for the same shapes. ``subject`` names the
        worker (``worker/<id>``)."""
        if predicted_s <= 0.0:
            return None
        ratio = measured_s / predicted_s
        return self._evaluate(
            "cost_model_drift", subject,
            bad=ratio >= self.drift_ratio,
            good=ratio <= self.drift_ratio * _CLEAR_SLACK,
            value=measured_s, expected=predicted_s, ratio=ratio,
            detail=f"{phase} step {measured_s * 1e3:.1f}ms vs model "
                   f"{predicted_s * 1e3:.1f}ms",
        )

    def observe_wire(
        self, wire: str, bandwidth_bytes_s: float
    ) -> Optional[HealthEvent]:
        """Wire-bandwidth collapse vs the EWMA's own history: the detector
        keeps a slow reference EWMA per wire and trips when the live
        estimate falls under ``collapse_frac`` of it. The reference only
        learns while untripped, so a collapse cannot drag its own baseline
        down and silence the alarm."""
        subject = f"wire/{wire}"
        st = self._states.setdefault(("wire_collapse", subject),
                                     _SubjectState())
        st.obs += 1
        if st.reference is None:
            st.reference = bandwidth_bytes_s
        ref = st.reference
        armed = st.obs > _MIN_REFERENCE_OBS and ref > 0.0
        ratio = bandwidth_bytes_s / ref if ref > 0 else 1.0
        ev = self._evaluate(
            "wire_collapse", subject,
            bad=armed and ratio <= self.collapse_frac,
            good=(not armed) or ratio >= min(
                self.collapse_frac / _CLEAR_SLACK, 1.0
            ),
            value=bandwidth_bytes_s, expected=ref, ratio=ratio,
            detail=f"{bandwidth_bytes_s / 1e6:.1f} MB/s vs reference "
                   f"{ref / 1e6:.1f} MB/s",
            state=st,
        )
        if not st.tripped:
            st.reference = (
                (1.0 - _REFERENCE_ALPHA) * ref
                + _REFERENCE_ALPHA * bandwidth_bytes_s
            )
        return ev

    def observe_hit_rate(
        self, subject: str, rate: float
    ) -> Optional[HealthEvent]:
        """Radix/global-KV hit-rate drop vs the subject's own baseline
        EWMA. ``subject`` e.g. ``radix/worker0`` or ``global_kv``."""
        st = self._states.setdefault(("hitrate_drop", subject),
                                     _SubjectState())
        st.obs += 1
        if st.reference is None:
            st.reference = rate
        ref = st.reference
        # an always-cold cache (tiny baseline) has nothing to drop from
        armed = st.obs > _MIN_REFERENCE_OBS and ref >= 0.05
        ratio = rate / ref if ref > 0 else 1.0
        ev = self._evaluate(
            "hitrate_drop", subject,
            bad=armed and ratio <= self.hitrate_drop,
            good=(not armed) or ratio >= min(
                self.hitrate_drop / _CLEAR_SLACK, 1.0
            ),
            value=rate, expected=ref, ratio=ratio,
            detail=f"hit rate {rate:.3f} vs baseline {ref:.3f}",
            state=st,
        )
        if not st.tripped:
            st.reference = (1.0 - _REFERENCE_ALPHA) * ref + _REFERENCE_ALPHA * rate
        return ev

    def observe_burn(
        self, model: str, sla_class: str,
        short_burn: Optional[float], long_burn: Optional[float],
    ) -> Optional[HealthEvent]:
        """Burn-rate acceleration: a class whose short-window error-budget
        burn runs ``burn_accel``x ahead of its long-window burn (and is
        itself over budget) is degrading NOW, not historically."""
        if short_burn is None:
            return None
        base = max(long_burn if long_burn is not None else 0.0, 1.0)
        ratio = short_burn / base
        return self._evaluate(
            "burn_rate_accel", f"class/{model}/{sla_class}",
            bad=ratio >= self.burn_accel and short_burn > 1.0,
            good=ratio <= self.burn_accel * _CLEAR_SLACK,
            value=short_burn, expected=base, ratio=ratio,
            detail=f"short-window burn {short_burn:.2f} vs long {base:.2f}",
        )

    def check_burn(self, accountant, window: str = "1m",
                   baseline: str = "1h") -> List[HealthEvent]:
        """Sweep an SloAccountant's classes through observe_burn."""
        out = []
        for model, cls in accountant.keys():
            ev = self.observe_burn(
                model, cls,
                accountant.burn_rate(model, cls, window),
                accountant.burn_rate(model, cls, baseline),
            )
            if ev is not None:
                out.append(ev)
        return out

    # -- the shared hysteresis/rate-limit core -------------------------------
    def _evaluate(
        self, detector: str, subject: str, *, bad: bool, good: bool,
        value: float, expected: float, ratio: float, detail: str,
        state: Optional[_SubjectState] = None,
    ) -> Optional[HealthEvent]:
        st = state if state is not None else self._states.setdefault(
            (detector, subject), _SubjectState()
        )
        now = self._clock()
        emitted: Optional[HealthEvent] = None
        if bad:
            st.bad += 1
            st.good = 0
            should_fire = st.bad >= _TRIP_N
            if should_fire and (
                not st.tripped or now - st.last_emit >= self.min_interval_s
            ):
                st.tripped = True
                st.last_emit = now
                emitted = HealthEvent(
                    detector, subject, "degraded", value, expected, ratio,
                    now, detail,
                )
        elif good:
            st.good += 1
            st.bad = 0
            if st.tripped and st.good >= _CLEAR_N:
                st.tripped = False
                st.last_emit = now
                emitted = HealthEvent(
                    detector, subject, "recovered", value, expected, ratio,
                    now, detail,
                )
        else:
            # the no-flap band between clear and trip thresholds: reset the
            # consecutive counters, change nothing
            st.bad = 0
            st.good = 0
        if emitted is not None:
            self._emit(emitted)
        return emitted

    def _emit(self, ev: HealthEvent) -> None:
        self.recent.append(ev)
        self.counts[ev.detector] = self.counts.get(ev.detector, 0) + 1
        if self._events_c is not None:
            self._events_c.inc(detector=ev.detector, kind=ev.kind)
        (log.warning if ev.kind == "degraded" else log.info)(
            "health: %s %s on %s (ratio %.2f; %s)",
            ev.detector, ev.kind, ev.subject, ev.ratio, ev.detail,
        )
        flight = self._flight
        if flight is None:
            from .flight_recorder import get_flight_recorder

            flight = get_flight_recorder()
        # synthetic per-detector timelines: "what degraded on this worker"
        # is answerable from /debug/requests like any request post-mortem
        flight.record(
            f"health:{ev.detector}", ev.kind,
            subject=ev.subject, ratio=round(ev.ratio, 4),
            value=round(ev.value, 6), expected=round(ev.expected, 6),
            detail=ev.detail,
        )
        for sub in list(self._subscribers):
            try:
                sub._callback(ev)
            except Exception:
                # a broken subscriber (event-plane hiccup) must not take
                # the detector path down
                log.exception("health subscriber failed for %s", ev.detector)

    # -- consumer side -------------------------------------------------------
    def active(self) -> List[Dict[str, Any]]:
        return [
            {"detector": det, "subject": subj}
            for (det, subj), st in sorted(self._states.items())
            if st.tripped
        ]

    def snapshot(self) -> Dict[str, Any]:
        return {
            "active": self.active(),
            "counts": dict(sorted(self.counts.items())),
            "recent": [ev.to_dict() for ev in list(self.recent)[-32:]],
        }

    def close(self) -> None:
        self._subscribers.clear()


_global_monitor: Optional[HealthMonitor] = None


def get_health_monitor() -> HealthMonitor:
    global _global_monitor
    if _global_monitor is None:
        _global_monitor = HealthMonitor()
    return _global_monitor


def set_health_monitor(monitor: Optional[HealthMonitor]) -> None:
    global _global_monitor
    _global_monitor = monitor


class StatusServer:
    """Side-port HTTP server exposing component health and metrics.

    Routes (reference system_status_server.rs:159-215):
      /health    aggregated HealthState (+ canary RTTs), 503 when unhealthy
      /live      process liveness (always 200 while serving)
      /metrics   Prometheus exposition from the runtime registry
      /metadata  caller-provided component metadata (model, config, snapshot)
      /v1/loras  loaded LoRA adapters (system_status_server.rs:196-215)
      /debug/requests  flight-recorder timelines (runtime/flight_recorder.py);
                 ``?id=<request_id>`` returns one timeline, 404 if evicted
      /debug/slo  per-(model, sla_class) attainment/burn-rate/goodput ledger
                 (runtime/slo.py SloAccountant; the worker-side view fed
                 from engine milestone timestamps)
      /debug/worker  the worker's one-call observability document (engine
                 snapshot, step telemetry, SLO ledger, attribution windows,
                 KV directory stats, drain state, restore mode, health
                 events) — the unit the frontend's ``/debug/fleet`` fan-out
                 merges (llm/fleet.py)
      POST /drain  planned-reclaim notice (engine/drain.py DrainCoordinator;
                 docs/operations.md §13): body ``{"deadline_s": 30}`` —
                 flips discovery to `draining`, evacuates/checkpoints, 409
                 when no drain handler is wired
    """

    def __init__(
        self,
        state: HealthState,
        metrics_scope: Optional[M.MetricsScope] = None,
        metadata_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        pre_expose: Optional[Callable[[], None]] = None,
        host: Optional[str] = None,
        port: int = 0,
        loras_fn: Optional[Callable[[], list]] = None,
        flight_recorder=None,
        drain_fn: Optional[Callable[[Optional[float]], Awaitable[Dict[str, Any]]]] = None,
        worker_snapshot_fn: Optional[Callable[[], Dict[str, Any]]] = None,
    ):
        self.state = state
        self.metrics = metrics_scope
        self.metadata_fn = metadata_fn
        self.loras_fn = loras_fn
        self.drain_fn = drain_fn
        self.worker_snapshot_fn = worker_snapshot_fn
        self.pre_expose = pre_expose  # refresh gauges right before scraping
        # explicit host wins; DTPU_SYSTEM_HOST configures what callers left open
        self.host = host if host is not None else env_str(ENV_SYSTEM_HOST, "0.0.0.0")
        self.port = port
        # None = the process-global recorder (workers get /debug/requests
        # without wiring); tests pass their own
        self._flight_recorder = flight_recorder
        self.started_at = time.time()
        self._runner: Optional[web.AppRunner] = None
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/metadata", self._metadata)
        app.router.add_get("/v1/loras", self._loras)
        app.router.add_get("/debug/requests", self._debug_requests)
        app.router.add_get("/debug/slo", self._debug_slo)
        app.router.add_get("/debug/worker", self._debug_worker)
        app.router.add_post("/drain", self._drain)
        self.app = app

    async def _health(self, request: web.Request) -> web.Response:
        snap = self.state.snapshot()
        return web.json_response(snap, status=200 if self.state.healthy else 503)

    async def _live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live", "uptime_s": time.time() - self.started_at})

    async def _metrics(self, request: web.Request) -> web.Response:
        if self.pre_expose is not None:
            try:
                self.pre_expose()
            except Exception:
                # stale gauges beat a failed scrape
                log.exception("metrics pre_expose hook failed")
        body = self.metrics.expose() if self.metrics is not None else b""
        return web.Response(body=body, content_type="text/plain")

    async def _metadata(self, request: web.Request) -> web.Response:
        meta = self.metadata_fn() if self.metadata_fn is not None else {}
        return web.json_response(meta)

    async def _loras(self, request: web.Request) -> web.Response:
        names = self.loras_fn() if self.loras_fn is not None else []
        return web.json_response({"data": [{"id": n} for n in names]})

    async def _debug_requests(self, request: web.Request) -> web.Response:
        from .flight_recorder import debug_requests_payload, get_flight_recorder

        rec = self._flight_recorder or get_flight_recorder()
        status, payload = debug_requests_payload(
            rec, request.query.get("id"), request.query.get("limit")
        )
        return web.json_response(payload, status=status)

    async def _debug_slo(self, request: web.Request) -> web.Response:
        from .slo import debug_slo_payload, get_slo_accountant

        return web.json_response(debug_slo_payload(get_slo_accountant()))

    async def _debug_worker(self, request: web.Request) -> web.Response:
        if self.worker_snapshot_fn is not None:
            try:
                doc = self.worker_snapshot_fn()
            except Exception as e:  # a broken section must not 500 the probe
                log.exception("worker snapshot assembly failed")
                doc = {"error": f"snapshot failed: {e}"}
        else:
            # minimal fallback so every StatusServer answers the fleet
            # fan-out with something mergeable
            doc = {"health": self.state.snapshot()}
        doc = dict(doc, uptime_s=round(time.time() - self.started_at, 3))
        return web.json_response(doc)

    async def _drain(self, request: web.Request) -> web.Response:
        if self.drain_fn is None:
            return web.json_response(
                {"error": "no drain handler on this component"}, status=409
            )
        deadline_s: Optional[float] = None
        try:
            body = await request.json()
        except Exception:
            body = {}
        raw = body.get("deadline_s", request.query.get("deadline_s"))
        if raw is not None:
            try:
                deadline_s = float(raw)
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": f"bad deadline_s {raw!r}"}, status=400
                )
        summary = await self.drain_fn(deadline_s)
        return web.json_response(summary)

    async def start(self) -> str:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("status server on %s:%d", self.host, self.port)
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
