"""Per-wire KV-transfer bandwidth estimation (the NetKV-style cost signal).

Disagg routing used to price a candidate by KV overlap + queue depth alone;
the wire between the prefill and decode instance was free in the model even
though the four transfer paths (``ici`` device fabric, cross-process
``device`` pulls, the ``native`` C++ agent, msgpack ``inline`` payloads)
span ~two orders of magnitude of real bandwidth. This module keeps one
process-wide EWMA of observed bytes/second per wire class, seeded with
static priors so routing is sane before the first transfer lands, and fed
by ``KvTransferClient`` from the same measurements the ``kv.transfer.pull``
spans record.

Estimates are deliberately coarse (per wire class, not per peer): the
estimator prices *which path* a transfer would take, and routing only needs
enough resolution to rank "same-slice ICI hop" above "msgpack over DCN".
``transfer_seconds(wire, nbytes)`` is the scoring primitive PrefillRouter
and the fleet simulator share.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from . import metrics as M

# static priors (bytes/second), used until a wire class has observations.
# Order-of-magnitude figures: ICI moves pages HBM->HBM on the pod fabric,
# the PJRT device plane streams over ICI/DCN with protocol overhead, the
# native agent is a raw-TCP memcpy loop, and inline rides msgpack on the
# asyncio request plane.
WIRE_PRIORS: Dict[str, float] = {
    "ici": 4.0e10,
    "device": 1.0e10,
    "native": 2.0e9,
    "inline": 5.0e8,
}
DEFAULT_WIRE = "inline"  # the pessimistic assumption for an unknown path


class WireBandwidthEstimator:
    """EWMA of observed per-wire bandwidth, seeded with static priors.

    Thread-safe: observations arrive from transfer client coroutines and
    executor threads; reads come from routing hot paths. ``alpha`` weights
    the newest observation (0.3 ~ a ~3-transfer memory, responsive to a
    congested wire without thrashing on one outlier).
    """

    def __init__(
        self,
        alpha: float = 0.3,
        priors: Optional[Dict[str, float]] = None,
        metrics: Optional[M.MetricsScope] = None,
    ):
        self.alpha = float(alpha)
        self.priors = dict(WIRE_PRIORS)
        if priors:
            self.priors.update(priors)
        self._ewma: Dict[str, float] = {}
        self._observations: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._gauge = (
            metrics.gauge(
                M.KV_WIRE_BANDWIDTH,
                "EWMA of observed KV transfer bandwidth per wire class",
                extra_labels=("wire",),
            )
            if metrics is not None else None
        )

    def attach_metrics(self, metrics: M.MetricsScope) -> None:
        """Late-bind a metrics scope (the process singleton is created
        before any registry exists)."""
        self._gauge = metrics.gauge(
            M.KV_WIRE_BANDWIDTH,
            "EWMA of observed KV transfer bandwidth per wire class",
            extra_labels=("wire",),
        )

    def observe(self, wire: str, nbytes: int, seconds: float) -> None:
        """Fold one completed transfer leg into the wire's estimate.
        Degenerate samples (zero bytes, non-positive duration — e.g. a
        fully-cached pull) are ignored rather than polluting the EWMA."""
        if nbytes <= 0 or seconds <= 0:
            return
        bw = nbytes / seconds
        with self._lock:
            prev = self._ewma.get(wire)
            cur = bw if prev is None else prev + self.alpha * (bw - prev)
            self._ewma[wire] = cur
            self._observations[wire] = self._observations.get(wire, 0) + 1
        if self._gauge is not None:
            self._gauge.set(cur, wire=wire)

    def bandwidth(self, wire: str) -> float:
        """Bytes/second for a wire class: the EWMA when observed, else the
        static prior (unknown classes price as DEFAULT_WIRE)."""
        with self._lock:
            est = self._ewma.get(wire)
        if est is not None:
            return est
        return self.priors.get(wire, self.priors[DEFAULT_WIRE])

    def transfer_seconds(self, wire: str, nbytes: int) -> float:
        """The scoring primitive: estimated seconds to move ``nbytes`` over
        ``wire``. 0 bytes is free regardless of the wire."""
        if nbytes <= 0:
            return 0.0
        return nbytes / self.bandwidth(wire)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Point-in-time view for /debug surfaces and reports:
        {wire: {bandwidth, observations, prior}}."""
        with self._lock:
            wires = set(self.priors) | set(self._ewma)
            return {
                w: {
                    "bandwidth_bytes_s": self._ewma.get(
                        w, self.priors.get(w, self.priors[DEFAULT_WIRE])
                    ),
                    "observations": self._observations.get(w, 0),
                    "prior_bytes_s": self.priors.get(
                        w, self.priors[DEFAULT_WIRE]
                    ),
                }
                for w in sorted(wires)
            }


_estimator: Optional[WireBandwidthEstimator] = None
_estimator_lock = threading.Lock()


def get_bandwidth_estimator() -> WireBandwidthEstimator:
    """The process-wide estimator every transfer client feeds and every
    router reads (one process observes one network position)."""
    global _estimator
    if _estimator is None:
        with _estimator_lock:
            if _estimator is None:
                _estimator = WireBandwidthEstimator()
    return _estimator
