"""Distributed runtime: component model, request/event planes, discovery."""

from .component import (
    Client,
    Component,
    Endpoint,
    Instance,
    Namespace,
    RouterMode,
    ServedEndpoint,
)
from .config import RuntimeConfig
from .discovery.store import (
    EventType,
    FileKVStore,
    KVStore,
    MemKVStore,
    WatchEvent,
    make_store,
)
from .distributed import DistributedRuntime, make_runtime
from .engine import AsyncEngine, Context, FnEngine, Operator, collect
from .errors import ContextLengthError, GuidedRejectedError, InvalidRequestError
from .event_plane.base import EventPlane, InProcEventPlane, Subscription
from .faults import FAULTS, FaultInjected, FaultRegistry, InjectedDrop
from .health import EndpointCanary, HealthState, StatusServer
from .logging import get_logger, init_logging
from .metrics import MetricsScope
from .request_plane.tcp import NoResponders, RequestPlaneError, TcpClient, TcpRequestServer
from .resilience import CircuitBreaker, CircuitOpenError, RetryPolicy

__all__ = [
    "AsyncEngine",
    "CircuitBreaker",
    "CircuitOpenError",
    "Client",
    "Component",
    "Context",
    "ContextLengthError",
    "DistributedRuntime",
    "Endpoint",
    "EndpointCanary",
    "EventPlane",
    "EventType",
    "FAULTS",
    "FaultInjected",
    "FaultRegistry",
    "GuidedRejectedError",
    "HealthState",
    "StatusServer",
    "FileKVStore",
    "FnEngine",
    "InProcEventPlane",
    "InjectedDrop",
    "InvalidRequestError",
    "Instance",
    "KVStore",
    "MemKVStore",
    "MetricsScope",
    "Namespace",
    "NoResponders",
    "Operator",
    "RequestPlaneError",
    "RetryPolicy",
    "RouterMode",
    "RuntimeConfig",
    "ServedEndpoint",
    "Subscription",
    "TcpClient",
    "TcpRequestServer",
    "WatchEvent",
    "collect",
    "get_logger",
    "init_logging",
    "make_runtime",
    "make_store",
]
