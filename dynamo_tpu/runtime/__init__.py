"""Distributed runtime: component model, request/event planes, discovery."""

from .component import (
    Client,
    Component,
    Endpoint,
    Instance,
    Namespace,
    RouterMode,
    ServedEndpoint,
)
from .config import RuntimeConfig
from .discovery.store import (
    EventType,
    FileKVStore,
    KVStore,
    MemKVStore,
    WatchEvent,
    make_store,
)
from .distributed import DistributedRuntime, make_runtime
from .engine import AsyncEngine, Context, FnEngine, Operator, collect
from .event_plane.base import EventPlane, InProcEventPlane, Subscription
from .health import EndpointCanary, HealthState, StatusServer
from .logging import get_logger, init_logging
from .metrics import MetricsScope
from .request_plane.tcp import NoResponders, RequestPlaneError, TcpClient, TcpRequestServer

__all__ = [
    "AsyncEngine",
    "Client",
    "Component",
    "Context",
    "DistributedRuntime",
    "Endpoint",
    "EndpointCanary",
    "EventPlane",
    "EventType",
    "HealthState",
    "StatusServer",
    "FileKVStore",
    "FnEngine",
    "InProcEventPlane",
    "Instance",
    "KVStore",
    "MemKVStore",
    "MetricsScope",
    "Namespace",
    "NoResponders",
    "Operator",
    "RequestPlaneError",
    "RouterMode",
    "RuntimeConfig",
    "ServedEndpoint",
    "Subscription",
    "TcpClient",
    "TcpRequestServer",
    "WatchEvent",
    "collect",
    "get_logger",
    "init_logging",
    "make_runtime",
    "make_store",
]
