"""Structured logging: human-readable or JSONL, env-configured.

Analog of the reference's logging layer (lib/runtime/src/logging.rs) minus the
OTLP exporter (gated: zero-egress environments); trace/request ids propagate
through a contextvar and are stamped on every record.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import sys
import time
from typing import Optional

from .config import ENV_LOG, ENV_LOG_JSONL, is_truthy

_request_id: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "dtpu_request_id", default=None
)

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def set_request_id(rid: Optional[str]) -> None:
    _request_id.set(rid)


def get_request_id() -> Optional[str]:
    return _request_id.get()


class _JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        rec = {
            "ts": round(time.time(), 6),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        rid = _request_id.get()
        if rid:
            rec["request_id"] = rid
        if record.exc_info and record.exc_info[0] is not None:
            rec["exception"] = self.formatException(record.exc_info)
        return json.dumps(rec, separators=(",", ":"))


class _TextFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        rid = _request_id.get()
        prefix = f"[{rid[:8]}] " if rid else ""
        base = super().format(record)
        return base.replace(record.getMessage(), prefix + record.getMessage(), 1)


_initialized = False


def init_logging(level: Optional[str] = None, jsonl: Optional[bool] = None) -> None:
    """Idempotent root logger setup for the dynamo_tpu.* hierarchy."""
    global _initialized
    if _initialized:
        return
    _initialized = True
    lvl = _LEVELS.get((level or os.environ.get(ENV_LOG, "info")).lower(), logging.INFO)
    use_jsonl = jsonl if jsonl is not None else is_truthy(os.environ.get(ENV_LOG_JSONL))
    handler = logging.StreamHandler(sys.stderr)
    if use_jsonl:
        handler.setFormatter(_JsonlFormatter())
    else:
        handler.setFormatter(
            _TextFormatter("%(asctime)s %(levelname)-5s %(name)s: %(message)s", "%H:%M:%S")
        )
    root = logging.getLogger("dynamo_tpu")
    root.setLevel(lvl)
    root.addHandler(handler)
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    if not name.startswith("dynamo_tpu"):
        name = f"dynamo_tpu.{name}"
    return logging.getLogger(name)
