"""Hierarchical Prometheus metrics.

Analog of the reference's metrics registry hierarchy
DRT -> Namespace -> Component -> Endpoint (lib/runtime/src/metrics.rs) and its
canonical name catalog (lib/runtime/src/metrics/prometheus_names.rs).

Each level of the component tree owns a ``MetricsScope`` that stamps
``dtpu_namespace`` / ``dtpu_component`` / ``dtpu_endpoint`` labels onto every
metric created beneath it, all backed by one ``CollectorRegistry`` per
DistributedRuntime so ``/metrics`` exposes everything in one scrape.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Tuple

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

# Canonical metric name fragments (keep in one place, like prometheus_names.rs)
PREFIX = "dtpu"

REQUESTS_TOTAL = f"{PREFIX}_requests_total"
REQUEST_DURATION_SECONDS = f"{PREFIX}_request_duration_seconds"
INFLIGHT_REQUESTS = f"{PREFIX}_inflight_requests"
QUEUED_REQUESTS = f"{PREFIX}_queued_requests"
TTFT_SECONDS = f"{PREFIX}_time_to_first_token_seconds"
ITL_SECONDS = f"{PREFIX}_inter_token_latency_seconds"
INPUT_TOKENS = f"{PREFIX}_input_tokens_total"
OUTPUT_TOKENS = f"{PREFIX}_output_tokens_total"
KV_ACTIVE_BLOCKS = f"{PREFIX}_kv_active_blocks"
KV_TOTAL_BLOCKS = f"{PREFIX}_kv_total_blocks"
KV_HIT_TOKENS = f"{PREFIX}_kv_cached_tokens_total"
WORKER_ACTIVE_DECODE_BLOCKS = f"{PREFIX}_worker_active_decode_blocks"
# engine step telemetry (engine/telemetry.py): per-step loop observability
KV_FREE_BLOCKS = f"{PREFIX}_kv_free_blocks"
STEP_DURATION_SECONDS = f"{PREFIX}_engine_step_duration_seconds"
STEP_TOKENS = f"{PREFIX}_engine_tokens_per_step"
BATCH_OCCUPANCY = f"{PREFIX}_engine_batch_occupancy"
SPEC_ACCEPTANCE = f"{PREFIX}_engine_spec_acceptance_rate"
SLOW_STEPS_TOTAL = f"{PREFIX}_engine_slow_steps_total"
# resilience (runtime/resilience.py): per-policy retry/breaker observability
KV_WIRE_BANDWIDTH = f"{PREFIX}_kv_wire_bandwidth_bytes_per_s"
PREFILL_DEFLECTED_TOTAL = f"{PREFIX}_prefill_deflected_total"
# SLO accounting plane (runtime/slo.py): per-(model, sla_class) promises
SLO_ATTAINMENT = f"{PREFIX}_slo_attainment_ratio"
SLO_BURN_RATE = f"{PREFIX}_slo_burn_rate"
GOODPUT_TOKENS = f"{PREFIX}_goodput_tokens_total"

# critical-path attribution (runtime/attribution.py): per-request phase
# decomposition that sums to the e2e duration
REQUEST_PHASE_SECONDS = f"{PREFIX}_request_phase_seconds"
# degradation detectors (runtime/health.py): typed, rate-limited events
HEALTH_EVENTS_TOTAL = f"{PREFIX}_health_events_total"

# fleet-wide KV reuse (kvbm/directory.py): global block directory + peer-
# tier fetch accounting
GLOBAL_KV_HITS_TOTAL = f"{PREFIX}_global_kv_hits_total"
GLOBAL_KV_DIRECTORY_ENTRIES = f"{PREFIX}_global_kv_directory_entries"
GLOBAL_KV_DEDUP_BLOCKS_TOTAL = f"{PREFIX}_global_kv_dedup_blocks_total"

# planned reclaims (engine/drain.py, engine/checkpoint.py)
DRAIN_EVACUATED_BLOCKS = f"{PREFIX}_drain_evacuated_blocks_total"
DRAIN_DEADLINE_MARGIN = f"{PREFIX}_drain_deadline_margin_seconds"
CHECKPOINT_RESTORE_MODE = f"{PREFIX}_checkpoint_restore_mode"

RETRY_ATTEMPTS_TOTAL = f"{PREFIX}_retry_attempts_total"
RETRY_GIVEUPS_TOTAL = f"{PREFIX}_retry_giveups_total"
CIRCUIT_STATE = f"{PREFIX}_circuit_state"
CIRCUIT_TRANSITIONS_TOTAL = f"{PREFIX}_circuit_transitions_total"

LABEL_NAMESPACE = "dtpu_namespace"
LABEL_COMPONENT = "dtpu_component"
LABEL_ENDPOINT = "dtpu_endpoint"
LABEL_MODEL = "model"
LABEL_SLA_CLASS = "sla_class"
LABEL_WINDOW = "window"


class MetricsScope:
    """A labelled view over a shared registry; child scopes append labels."""

    def __init__(
        self,
        registry: Optional[CollectorRegistry] = None,
        const_labels: Optional[Dict[str, str]] = None,
        _cache: Optional[Dict[Tuple[str, str], object]] = None,
        _lock: Optional[threading.Lock] = None,
    ):
        self.registry = registry or CollectorRegistry()
        self.const_labels: Dict[str, str] = dict(const_labels or {})
        # metric objects are shared across scopes (prometheus_client forbids
        # re-registering a name), keyed by (kind, name, labelnames)
        self._cache: Dict[Tuple, object] = _cache if _cache is not None else {}
        self._lock = _lock if _lock is not None else threading.Lock()

    def child(self, **labels: str) -> "MetricsScope":
        merged = dict(self.const_labels)
        merged.update(labels)
        return MetricsScope(self.registry, merged, self._cache, self._lock)

    # -- metric constructors ------------------------------------------------
    def _get(self, kind: str, cls, name: str, doc: str, extra_labels: Iterable[str], **kw):
        # prometheus_client allows one collector per name per registry, so the
        # label set is fixed at first creation. Always include the hierarchy
        # labels so creation order (root vs child scope) doesn't matter; the
        # registered labelnames are authoritative on cache hits and _Bound
        # fills any label it has no value for with "".
        labelnames = tuple(
            sorted(
                {LABEL_NAMESPACE, LABEL_COMPONENT, LABEL_ENDPOINT}
                | set(self.const_labels)
                | set(extra_labels)
            )
        )
        with self._lock:
            key = (kind, name)
            entry = self._cache.get(key)
            if entry is None:
                metric = cls(name, doc, labelnames=labelnames, registry=self.registry, **kw)
                self._cache[key] = (metric, labelnames)
            else:
                metric, labelnames = entry
        return metric, labelnames

    def counter(self, name: str, doc: str = "", extra_labels: Iterable[str] = ()):
        metric, labelnames = self._get("counter", Counter, name, doc, extra_labels)
        return _Bound(metric, self.const_labels, labelnames)

    def gauge(self, name: str, doc: str = "", extra_labels: Iterable[str] = ()):
        metric, labelnames = self._get("gauge", Gauge, name, doc, extra_labels)
        return _Bound(metric, self.const_labels, labelnames)

    def histogram(self, name: str, doc: str = "", extra_labels: Iterable[str] = (), buckets=None):
        kw = {"buckets": buckets} if buckets else {}
        metric, labelnames = self._get("histogram", Histogram, name, doc, extra_labels, **kw)
        return _Bound(metric, self.const_labels, labelnames)

    def expose(self) -> bytes:
        return generate_latest(self.registry)


class _Bound:
    """A metric pre-bound to the scope's constant labels; extra labels fill at use."""

    __slots__ = ("_metric", "_const", "_labelnames")

    def __init__(self, metric, const: Dict[str, str], labelnames: Tuple[str, ...]):
        self._metric = metric
        self._const = const
        self._labelnames = labelnames

    def _resolve(self, extra: Dict[str, str]):
        values = {}
        for ln in self._labelnames:
            if ln in extra:
                values[ln] = extra[ln]
            elif ln in self._const:
                values[ln] = self._const[ln]
            else:
                values[ln] = ""
        if not self._labelnames:
            return self._metric
        return self._metric.labels(**values)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        self._resolve(labels).inc(amount)

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self._resolve(labels).dec(amount)

    def set(self, value: float, **labels: str) -> None:
        self._resolve(labels).set(value)

    def observe(self, value: float, **labels: str) -> None:
        self._resolve(labels).observe(value)
