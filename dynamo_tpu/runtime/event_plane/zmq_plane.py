"""ZMQ event plane: XPUB/XSUB broker + connecting pub/sub endpoints.

Analog of the reference's ZMQ event transport
(lib/runtime/src/transports/event_plane/zmq_transport.rs). Many publishers and
many subscribers meet at a small forwarding broker whose address lives in the
discovery store under ``v1/event_broker``; the first runtime to come up starts
it (lease-attached, so a crashed broker host is detected and replaced).

Wire format: multipart [topic: utf-8, payload: bytes].
"""

from __future__ import annotations

import asyncio
from typing import List, Optional

import zmq
import zmq.asyncio

from ..discovery.store import KVStore
from ..faults import FAULTS
from ..logging import get_logger
from ..resilience import retry_policy
from ..tasks import spawn_bg
from .base import EventPlane, Subscription

log = get_logger("runtime.event_plane.zmq")

BROKER_KEY = "v1/event_broker"


class ZmqBroker:
    """XSUB (publishers connect) <-> XPUB (subscribers connect) forwarder."""

    def __init__(self, host: str = "127.0.0.1"):
        self._host = host
        self._ctx = zmq.asyncio.Context.instance()
        self._xsub: Optional[zmq.asyncio.Socket] = None
        self._xpub: Optional[zmq.asyncio.Socket] = None
        self._task: Optional[asyncio.Task] = None
        self.pub_addr = ""   # where publishers connect (broker's XSUB bind)
        self.sub_addr = ""   # where subscribers connect (broker's XPUB bind)

    async def start(self) -> None:
        self._xsub = self._ctx.socket(zmq.XSUB)
        xsub_port = self._xsub.bind_to_random_port(f"tcp://{self._host}")
        self._xpub = self._ctx.socket(zmq.XPUB)
        self._xpub.setsockopt(zmq.XPUB_VERBOSE, 1)
        xpub_port = self._xpub.bind_to_random_port(f"tcp://{self._host}")
        self.pub_addr = f"tcp://{self._host}:{xsub_port}"
        self.sub_addr = f"tcp://{self._host}:{xpub_port}"
        # spawn_bg: a forwarder that dies on a ZMQ error must log, not
        # vanish silently with its exception unretrieved until GC
        self._task = spawn_bg(self._forward())
        log.debug("zmq broker up: pub=%s sub=%s", self.pub_addr, self.sub_addr)

    async def _forward(self) -> None:
        assert self._xsub is not None and self._xpub is not None
        poller = zmq.asyncio.Poller()
        poller.register(self._xsub, zmq.POLLIN)
        poller.register(self._xpub, zmq.POLLIN)
        try:
            while True:
                events = dict(await poller.poll())
                if self._xsub in events:
                    msg = await self._xsub.recv_multipart()
                    await self._xpub.send_multipart(msg)
                if self._xpub in events:
                    msg = await self._xpub.recv_multipart()  # subscription frames
                    await self._xsub.send_multipart(msg)
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        for s in (self._xsub, self._xpub):
            if s is not None:
                s.close(0)


class ZmqEventPlane(EventPlane):
    def __init__(self, pub_addr: str, sub_addr: str, broker: Optional[ZmqBroker] = None):
        self._ctx = zmq.asyncio.Context.instance()
        self._pub = self._ctx.socket(zmq.PUB)
        self._pub.connect(pub_addr)
        self._sub_addr = sub_addr
        self._broker = broker  # set if this plane founded the broker
        self._sub_tasks: List[asyncio.Task] = []
        self._sub_sockets: List[zmq.asyncio.Socket] = []
        self._warm_evt: Optional[asyncio.Event] = None

    async def _warm(self) -> None:
        """One slow-joiner beat, shared by every concurrent first publish.

        The old ``if not self._warmed: await sleep(); self._warmed = True``
        was a check-then-act across an await (ASYNC-RMW): every publish that
        arrived during the warm window re-checked the stale flag and served
        its own full sleep. The event is created synchronously (no await
        between check and act), so exactly one caller sleeps and the rest
        ride the same beat. If the elected sleeper is cancelled mid-beat it
        wakes the waiters and clears the slot so the next caller re-elects —
        otherwise one cancelled wait_for would deadlock every later publish.
        The EVENT-LIVENESS rule codifies this shape: a rollback that wakes
        then clears is only safe because every wait site here re-elects in
        the loop, and tests/test_analysis_contracts.py pins that the
        straight-line-waiter variant of this function fires the rule."""
        while True:
            if self._warm_evt is None:
                self._warm_evt = evt = asyncio.Event()
                try:
                    # PUB->broker connect is async; without a beat the first
                    # publishes are dropped on the floor (zmq slow-joiner).
                    await asyncio.sleep(0.15)
                except BaseException:
                    # deliberate rollback: the election itself is synchronous
                    # (check->assign with no await between); this write only
                    # undoes OUR election so a waiter can re-elect
                    self._warm_evt = None  # dtpu: ignore[ASYNC-RMW]
                    evt.set()  # wake waiters so one of them re-elects
                    raise
                evt.set()
                return
            evt = self._warm_evt
            if evt.is_set():
                return
            await evt.wait()
            if self._warm_evt is evt:
                return  # the sleeper finished the beat

    async def publish(self, topic: str, payload: bytes) -> None:
        await self._warm()

        async def send():
            await FAULTS.ainject("event_plane.publish")
            body = FAULTS.mangle("event_plane.publish", payload)
            await self._pub.send_multipart([topic.encode(), body])

        try:
            # shared policy (scope event_plane.publish): transient socket
            # errors retry; an exhausted retry DROPS the event (pub/sub is
            # best-effort; consumers resync from snapshots) instead of
            # crashing the publisher's loop
            await retry_policy(
                "event_plane.publish",
                max_attempts=3, base_delay_s=0.02, max_delay_s=0.5,
                retryable=(ConnectionError, OSError, zmq.ZMQError),
            ).acall(send)
        except (ConnectionError, OSError, zmq.ZMQError) as e:
            log.warning("event publish dropped (%s): %s", topic, e)

    async def subscribe(self, topic_prefix: str) -> Subscription:
        sock = self._ctx.socket(zmq.SUB)
        sock.connect(self._sub_addr)
        sock.setsockopt(zmq.SUBSCRIBE, topic_prefix.encode())
        self._sub_sockets.append(sock)
        sub = Subscription()

        async def recv_loop() -> None:
            try:
                while True:
                    topic, payload = await sock.recv_multipart()
                    sub._emit(topic.decode(), payload)
            except asyncio.CancelledError:
                pass
            except zmq.ZMQError:
                pass

        task = asyncio.create_task(recv_loop())
        self._sub_tasks.append(task)
        orig_cancel = sub.cancel

        def cancel() -> None:
            task.cancel()
            sock.close(0)
            orig_cancel()

        sub.cancel = cancel  # type: ignore[method-assign]
        await asyncio.sleep(0.15)  # let the broker see the subscription
        return sub

    async def close(self) -> None:
        for t in self._sub_tasks:
            t.cancel()
        for s in self._sub_sockets:
            s.close(0)
        self._pub.close(0)
        if self._broker is not None:
            await self._broker.stop()


async def event_plane_from_store(store: KVStore, lease_id: Optional[str] = None) -> EventPlane:
    """Join (or found) the process-shared ZMQ event plane via the store.

    Founding is racy (no compare-and-swap in the store interface), so after
    publishing our broker we re-read: if another founder's record won, we tear
    our broker down and join theirs — everyone converges on one broker.
    """
    rec = await store.get_obj(BROKER_KEY)
    if rec is not None:
        return ZmqEventPlane(rec["pub"], rec["sub"])
    broker = ZmqBroker()
    await broker.start()
    ours = {"pub": broker.pub_addr, "sub": broker.sub_addr}
    await store.put_obj(BROKER_KEY, ours, lease_id)
    await asyncio.sleep(0.05)  # let a concurrent founder's put land
    rec = await store.get_obj(BROKER_KEY) or ours
    if rec != ours:
        await broker.stop()
        return ZmqEventPlane(rec["pub"], rec["sub"])
    return ZmqEventPlane(rec["pub"], rec["sub"], broker=broker)
