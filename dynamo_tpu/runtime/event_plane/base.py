"""Event plane interface: fire-and-forget pub/sub for KV events + metrics.

Analog of the reference's event plane abstraction with NATS/ZMQ transports
(lib/runtime/src/transports/event_plane/). Topics are dot-separated strings;
subscriptions match by prefix. Payloads are opaque bytes (callers msgpack).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Optional, Tuple

from ..faults import FAULTS
from ..logging import get_logger

log = get_logger("runtime.event_plane")


class Subscription:
    def __init__(self):
        self._queue: asyncio.Queue = asyncio.Queue()
        self._closed = False

    def _emit(self, topic: str, payload: bytes) -> None:
        if not self._closed:
            self._queue.put_nowait((topic, payload))

    def __aiter__(self) -> AsyncIterator[Tuple[str, bytes]]:
        return self

    async def __anext__(self) -> Tuple[str, bytes]:
        item = await self._queue.get()
        if item is None:
            raise StopAsyncIteration
        return item

    async def next(self, timeout: Optional[float] = None) -> Optional[Tuple[str, bytes]]:
        try:
            item = await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return None
        return item

    def cancel(self) -> None:
        self._closed = True
        self._queue.put_nowait(None)


class EventPlane:
    async def publish(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    async def subscribe(self, topic_prefix: str) -> Subscription:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class InProcEventPlane(EventPlane):
    """Same-process pub/sub: deterministic and instant, the test default."""

    def __init__(self):
        self._subs: list = []  # (prefix, Subscription)

    async def publish(self, topic: str, payload: bytes) -> None:
        try:
            await FAULTS.ainject("event_plane.publish")
        except ConnectionError as e:
            # events are fire-and-forget: a dropped publish degrades
            # (consumers resync from snapshots), it must not crash the
            # publisher's loop
            log.warning("event publish dropped (%s): %s", topic, e)
            return
        payload = FAULTS.mangle("event_plane.publish", payload)
        for prefix, sub in list(self._subs):
            if topic.startswith(prefix):
                sub._emit(topic, payload)

    async def subscribe(self, topic_prefix: str) -> Subscription:
        sub = Subscription()
        self._subs.append((topic_prefix, sub))
        return sub

    async def close(self) -> None:
        for _, sub in self._subs:
            sub.cancel()
        self._subs.clear()
