"""Layered runtime configuration + centralized environment-variable catalog.

Analog of the reference's figment-based RuntimeConfig (lib/runtime/src/config.rs)
and its ``DYN_*`` env catalog (lib/runtime/src/config/environment_names.rs).
We use a ``DTPU_*`` prefix. Precedence: explicit kwargs > env > defaults
(code that passes a value means it; env configures what code left open).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional

# ---------------------------------------------------------------------------
# Environment variable catalog (single source of truth for names)
# ---------------------------------------------------------------------------

ENV_LOG = "DTPU_LOG"                                  # log level (debug/info/warn/error)
ENV_LOG_JSONL = "DTPU_LOGGING_JSONL"                  # structured JSONL logging on/off
ENV_REQUEST_PLANE = "DTPU_REQUEST_PLANE"              # tcp | http | inproc
ENV_EVENT_PLANE = "DTPU_EVENT_PLANE"                  # zmq | inproc
ENV_STORE = "DTPU_STORE"                              # mem | file | tcp | etcd
ENV_STORE_PATH = "DTPU_STORE_PATH"                    # file path / tcp host:port / etcd endpoint
ENV_SYSTEM_PORT = "DTPU_SYSTEM_PORT"                  # system status server port
ENV_SYSTEM_HOST = "DTPU_SYSTEM_HOST"
ENV_HOST_IP = "DTPU_HOST_IP"                          # advertised host for request plane
ENV_LEASE_TTL_S = "DTPU_LEASE_TTL_S"                  # discovery lease ttl
ENV_NAMESPACE = "DTPU_NAMESPACE"
ENV_KV_BLOCK_SIZE = "DTPU_KV_BLOCK_SIZE"              # tokens per kv block
ENV_ROUTER_REPLICA_SYNC = "DTPU_ROUTER_REPLICA_SYNC"
ENV_MIGRATION_LIMIT = "DTPU_MIGRATION_LIMIT"
ENV_WORKER_GRACEFUL_SHUTDOWN_TIMEOUT = "DTPU_WORKER_GRACEFUL_SHUTDOWN_TIMEOUT"
ENV_CANARY_WAIT_TIME = "DTPU_CANARY_WAIT_TIME"
ENV_KVBM_HOST_CACHE_GB = "DTPU_KVBM_HOST_CACHE_GB"    # G2 host DRAM pool size
ENV_KVBM_DISK_CACHE_GB = "DTPU_KVBM_DISK_CACHE_GB"    # G3 local disk pool size
ENV_KVBM_DISK_PATH = "DTPU_KVBM_DISK_PATH"
ENV_HTTP_PORT = "DTPU_HTTP_PORT"
ENV_BUSY_THRESHOLD = "DTPU_BUSY_THRESHOLD"
# observability (runtime/tracing.py, llm/audit.py)
ENV_AUDIT_SINKS = "DTPU_AUDIT_SINKS"                  # stderr,jsonl:<path>,event
ENV_AUDIT_FORCE_LOGGING = "DTPU_AUDIT_FORCE_LOGGING"  # audit every request
ENV_AUDIT_SUBJECT = "DTPU_AUDIT_SUBJECT"              # event-plane audit topic
ENV_OTLP_ENDPOINT = "DTPU_OTLP_ENDPOINT"              # OTLP/HTTP collector
ENV_TRACE_JSONL = "DTPU_TRACE_JSONL"                  # span JSONL file
# request flight recorder (runtime/flight_recorder.py) + step telemetry
ENV_FLIGHT_CAPACITY = "DTPU_FLIGHT_CAPACITY"          # retained request timelines
ENV_FLIGHT_DUMP = "DTPU_FLIGHT_DUMP"                  # JSONL path for failure dumps
ENV_SLOW_STEP_MS = "DTPU_SLOW_STEP_MS"                # slow-step log threshold
ENV_ASYNC_PREP = "DTPU_ASYNC_PREP"                    # async host step-prep on/off
# SLO accounting (runtime/slo.py)
ENV_SLA_CLASSES = "DTPU_SLA_CLASSES"                  # "interactive:ttft=0.5,itl=0.05;batch:ttft=30"
ENV_SLA_DEFAULT = "DTPU_SLA_DEFAULT"                  # class stamped when a request names none
ENV_SLO_OBJECTIVE = "DTPU_SLO_OBJECTIVE"              # attainment objective for burn rate (0.99)
# lora (lora/cache.py)
ENV_LORA_CACHE = "DTPU_LORA_CACHE"                    # adapter cache dir
# kvbm remote tier (kvbm/remote.py)
ENV_KVBM_REMOTE = "DTPU_KVBM_REMOTE"                  # G4 block store host:port
ENV_CONFIG_FILE = "DTPU_CONFIG"                       # layered config file (json/toml)
# resilience + chaos (runtime/resilience.py, runtime/faults.py).
# Retry/breaker scopes are layered specs: DTPU_RETRY_DEFAULT applies to every
# policy, DTPU_RETRY_<SCOPE> (scope upper-cased, dots -> underscores, e.g.
# DTPU_RETRY_TRANSFER_PULL) overrides per scope; same shape for DTPU_CB_*.
ENV_RETRY_DEFAULT = "DTPU_RETRY_DEFAULT"              # "attempts=3,base=0.05,max=2,timeout=10,deadline=30"
ENV_CB_DEFAULT = "DTPU_CB_DEFAULT"                    # "threshold=5,rate=0.5,window=30,reset=5,half_open=1"
ENV_FAULTS = "DTPU_FAULTS"                            # fault-injection spec, e.g. "transfer.pull:drop@2"
# engine + kernels (engine/engine.py, ops/quant.py, engine/warm.py,
# engine/weight_service.py, parallel/pp_serving.py, runtime/multihost.py)
ENV_MIXED = "DTPU_MIXED"                              # mixed continuous batching on/off/auto
ENV_KV_DTYPE = "DTPU_KV_DTYPE"                        # paged KV cache dtype (int8 opt-in)
ENV_LOOP_TRACE = "DTPU_LOOP_TRACE"                    # engine step-loop debug trace
ENV_WARM_CACHE = "DTPU_WARM_CACHE"                    # host weight cache dir
ENV_WEIGHT_SERVICE = "DTPU_WEIGHT_SERVICE"            # shared weight service address
ENV_WEIGHT_SHM = "DTPU_WEIGHT_SHM"                    # weight shm segment prefix
ENV_PP_MICROBATCHES = "DTPU_PP_MICROBATCHES"          # pp wavefront microbatch count
ENV_PP_COND_SKIP = "DTPU_PP_COND_SKIP"                # pp conditional bubble skip
ENV_MH_TRACE = "DTPU_MH_TRACE"                        # multihost replay debug trace
# KV transfer plane (engine/transfer.py, transfer/native.py)
ENV_STREAM_WINDOW = "DTPU_STREAM_WINDOW"              # streamed fetch window (blocks)
ENV_STREAM_WAIT_S = "DTPU_STREAM_WAIT_S"              # streamed fetch commit-wait budget
ENV_DEVICE_TRANSFER = "DTPU_DEVICE_TRANSFER"          # device-to-device pull path on/off
ENV_ICI_TRANSFER = "DTPU_ICI_TRANSFER"                # same-process ICI fast path on/off
ENV_XFER_HOST = "DTPU_XFER_HOST"                      # advertised transfer-plane host
ENV_KV_WIRE = "DTPU_KV_WIRE"                          # advertised kv wire class (ici/tcp/...)
# router scale (kv_router/scheduler.py, docs/operations.md 9b)
ENV_ROUTER_TOPK = "DTPU_ROUTER_TOPK"                  # two-stage routing candidate K
ENV_ROUTER_SHARDS = "DTPU_ROUTER_SHARDS"              # postings/snapshot index shards
ENV_ROUTER_POSTINGS_BUCKET = "DTPU_ROUTER_POSTINGS_BUCKET"  # per-block postings cap
# disagg routing + prefill deflection (llm/prefill_router.py, PR 10 knobs)
ENV_STREAM_KV = "DTPU_STREAM_KV"                      # streamed (vs sequential) disagg dispatch
ENV_DEFLECT = "DTPU_DEFLECT"                          # prefill deflection valve on/off
ENV_DEFLECT_MAX_TOKENS = "DTPU_DEFLECT_MAX_TOKENS"    # short-prompt deflection bound
ENV_DEFLECT_OVERLAP = "DTPU_DEFLECT_OVERLAP"          # decode-pool radix-hit deflection share
ENV_DEFLECT_MARGIN = "DTPU_DEFLECT_MARGIN"            # load-skew deflection margin
ENV_PREFILL_BLOCK_MS = "DTPU_PREFILL_BLOCK_MS"        # per-block prefill cost prior
ENV_KV_BYTES_PER_BLOCK = "DTPU_KV_BYTES_PER_BLOCK"    # wire-cost bytes/block override
# fleet-wide KV reuse (kvbm/directory.py, llm/prefill_router.py): the global
# content-addressed block directory over the discovery plane + the
# fetch-vs-recompute decision (ops/costs.py)
ENV_GLOBAL_KV = "DTPU_GLOBAL_KV"                      # global KV directory on/off
ENV_GLOBAL_KV_TTL_S = "DTPU_GLOBAL_KV_TTL_S"          # directory entry ttl (s)
ENV_GLOBAL_KV_DEDUPE = "DTPU_GLOBAL_KV_DEDUPE"        # max advertised holders per hash
ENV_GLOBAL_KV_FETCH_MARGIN = "DTPU_GLOBAL_KV_FETCH_MARGIN"  # fetch <= margin*recompute gate
# fleet observability plane (runtime/health.py detectors, llm/fleet.py
# /debug/fleet fan-out)
ENV_FLEET_FANOUT = "DTPU_FLEET_FANOUT"                # /debug/fleet concurrent worker fetches
ENV_FLEET_TIMEOUT_S = "DTPU_FLEET_TIMEOUT_S"          # per-worker snapshot fetch timeout (s)
ENV_HEALTH_MIN_INTERVAL_S = "DTPU_HEALTH_MIN_INTERVAL_S"  # min s between health events per subject
ENV_HEALTH_DRIFT_RATIO = "DTPU_HEALTH_DRIFT_RATIO"    # measured/predicted step-time trip ratio
# planned reclaims + checkpoint/restore (engine/drain.py, engine/checkpoint.py)
ENV_DRAIN_DEADLINE_S = "DTPU_DRAIN_DEADLINE_S"        # default reclaim deadline (s)
ENV_DRAIN_MARGIN_S = "DTPU_DRAIN_MARGIN_S"            # stop evacuating this early (s)
ENV_CKPT_DIR = "DTPU_CKPT_DIR"                        # G3 checkpoint directory
ENV_CKPT_MAX_BLOCKS = "DTPU_CKPT_MAX_BLOCKS"          # sealed blocks per checkpoint cap
# model hub + media fetch (llm/hub.py, llm/media.py)
ENV_HUB_CACHE = "DTPU_HUB_CACHE"                      # checkpoint cache dir
ENV_HUB_OFFLINE = "DTPU_HUB_OFFLINE"                  # forbid hub network fetches
ENV_MEDIA_FILE_ROOT = "DTPU_MEDIA_FILE_ROOT"          # multimodal file:// jail root

_TRUTHY = {"1", "true", "yes", "on", "enabled"}
_FALSEY = {"0", "false", "no", "off", "disabled", ""}


def is_truthy(val: Optional[str]) -> bool:
    """Permissive env-var boolean parsing (reference: lib/config/src/lib.rs:20)."""
    if val is None:
        return False
    return val.strip().lower() in _TRUTHY


def is_falsey(val: Optional[str]) -> bool:
    if val is None:
        return True
    return val.strip().lower() in _FALSEY


def env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def env_bool(name: str, default: bool = False) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return is_truthy(raw)


@dataclasses.dataclass
class RuntimeConfig:
    """Top-level runtime knobs; every field has an env override."""

    request_plane: str = "tcp"           # tcp | http | inproc
    event_plane: str = "zmq"             # zmq | inproc
    store: str = "mem"                   # mem | file | etcd
    store_path: str = "/tmp/dtpu_store"
    host_ip: str = "127.0.0.1"
    system_port: int = 0                 # 0 = disabled
    lease_ttl_s: float = 10.0
    graceful_shutdown_timeout_s: float = 30.0

    @classmethod
    def from_env(cls, **overrides: Any) -> "RuntimeConfig":
        """Layered resolution (figment analog, lib/runtime/src/config.rs):
        defaults < config file (DTPU_CONFIG, json/toml) < env < kwargs."""
        base: Dict[str, Any] = {}
        cfg_file = os.environ.get(ENV_CONFIG_FILE)
        if cfg_file:
            base.update(load_config_file(cfg_file))
        def layered(field: str, env_name: str, conv) -> Any:
            default = getattr(cls, field)
            if field in base:
                # file values get the same coercion as env values (a JSON
                # string "9100" for a port must not flow through as str)
                try:
                    default = conv(base[field])
                except (TypeError, ValueError):
                    pass
            raw = os.environ.get(env_name)
            if raw is None or raw == "":
                return default
            try:
                return conv(raw)
            except (TypeError, ValueError):
                return default

        cfg = cls(
            request_plane=layered("request_plane", ENV_REQUEST_PLANE, str),
            event_plane=layered("event_plane", ENV_EVENT_PLANE, str),
            store=layered("store", ENV_STORE, str),
            store_path=layered("store_path", ENV_STORE_PATH, str),
            host_ip=layered("host_ip", ENV_HOST_IP, str),
            system_port=layered("system_port", ENV_SYSTEM_PORT, int),
            lease_ttl_s=layered("lease_ttl_s", ENV_LEASE_TTL_S, float),
            graceful_shutdown_timeout_s=layered(
                "graceful_shutdown_timeout_s",
                ENV_WORKER_GRACEFUL_SHUTDOWN_TIMEOUT, float,
            ),
        )
        for k, v in overrides.items():
            if v is not None:
                setattr(cfg, k, v)
        return cfg

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def load_config_file(path: str) -> Dict[str, Any]:
    """json or toml (stdlib tomllib); unknown keys are ignored by callers."""
    with open(path, "rb") as f:
        raw = f.read()
    if path.endswith(".toml"):
        try:
            import tomllib  # py3.11+
        except ImportError:
            try:
                import tomli as tomllib  # type: ignore[no-redef]
            except ImportError:
                import toml

                return toml.loads(raw.decode())
        return tomllib.loads(raw.decode())
    import json

    return json.loads(raw.decode())
