"""Streaming engine protocol and cancellation contexts.

TPU-native analog of the reference's ``AsyncEngine`` abstraction
(reference: lib/runtime/src/engine.rs:201) and its hierarchical
``AsyncEngineContext`` stop/kill propagation (lib/runtime/src/engine.rs:112).

Every unit of work in the framework — preprocessors, routers, engines — is an
async callable ``generate(request, context) -> AsyncIterator[response]``.
Cancellation is cooperative: ``Context.stop_generating()`` asks the producer to
wind down gracefully (emit what it has), ``Context.kill()`` demands immediate
teardown. Contexts form a tree so that cancelling a frontend request cancels
the nested prefill + decode work it spawned.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any, AsyncIterator, Callable, List, Optional, Protocol, runtime_checkable


class Context:
    """Cancellation + identity context for one in-flight request."""

    __slots__ = ("id", "_stopped", "_killed", "_children", "_parent", "_callbacks")

    def __init__(self, request_id: Optional[str] = None, parent: Optional["Context"] = None):
        self.id: str = request_id or uuid.uuid4().hex
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._children: List["Context"] = []
        self._parent = parent
        self._callbacks: List[Callable[[], None]] = []

    # -- tree ---------------------------------------------------------------
    def child(self, request_id: Optional[str] = None) -> "Context":
        c = Context(request_id or self.id, parent=self)
        if self.is_stopped():
            c._stopped.set()
        if self.is_killed():
            c._killed.set()
        self._children.append(c)
        return c

    def detach(self) -> None:
        if self._parent is not None and self in self._parent._children:
            self._parent._children.remove(self)
        self._parent = None

    # -- cancellation -------------------------------------------------------
    def stop_generating(self) -> None:
        """Graceful stop: producer should finish the current token and end."""
        self._stopped.set()
        for cb in self._callbacks:
            cb()
        for c in self._children:
            c.stop_generating()

    def kill(self) -> None:
        """Hard cancel: producer must abandon in-flight work."""
        self._killed.set()
        self._stopped.set()
        for cb in self._callbacks:
            cb()
        for c in self._children:
            c.kill()

    def on_cancel(self, cb: Callable[[], None]) -> None:
        self._callbacks.append(cb)
        if self.is_stopped():
            cb()

    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def stopped(self) -> None:
        await self._stopped.wait()

    async def killed(self) -> None:
        await self._killed.wait()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Context(id={self.id!r}, stopped={self.is_stopped()}, killed={self.is_killed()})"


@runtime_checkable
class AsyncEngine(Protocol):
    """Anything that turns a request into an async stream of responses."""

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        ...


class FnEngine:
    """Wrap a plain async-generator function as an AsyncEngine."""

    def __init__(self, fn: Callable[[Any, Context], AsyncIterator[Any]], name: str = "fn"):
        self._fn = fn
        self.name = name

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self._fn(request, context)

    def __repr__(self) -> str:  # pragma: no cover
        return f"FnEngine({self.name})"


class Operator:
    """A pipeline stage: transforms a request on the way in and the response
    stream on the way out, delegating to a downstream engine.

    Analog of the reference's pipeline operator nodes
    (lib/runtime/src/pipeline/nodes.rs) but expressed as plain composition:
    an Operator wraps the next engine rather than being wired into a
    source/sink graph — idiomatic for asyncio.
    """

    def __init__(self, downstream: AsyncEngine):
        self.downstream = downstream

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        async for item in self.downstream.generate(request, context):
            yield item


async def collect(stream: AsyncIterator[Any]) -> List[Any]:
    """Drain a response stream into a list (test/batch helper)."""
    out = []
    async for item in stream:
        out.append(item)
    return out
