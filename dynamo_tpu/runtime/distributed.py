"""DistributedRuntime: the per-process root object.

Analog of the reference's DistributedRuntime (lib/runtime/src/distributed.rs:42):
owns the discovery store connection, a primary lease with keepalive, the
request-plane client, the event plane, and the process metrics registry.
Everything else (namespaces, components, endpoints) hangs off it.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from .component import DistributedRuntimeBase
from .config import RuntimeConfig
from .discovery.store import KVStore, make_store
from .event_plane.base import EventPlane, InProcEventPlane
from .faults import FAULTS
from .logging import get_logger, init_logging
from .metrics import MetricsScope
from .request_plane.tcp import TcpClient
from .resilience import retry_policy
from .tasks import TaskTracker

log = get_logger("runtime.distributed")


class DistributedRuntime(DistributedRuntimeBase):
    def __init__(
        self,
        config: Optional[RuntimeConfig] = None,
        store: Optional[KVStore] = None,
        event_plane: Optional[EventPlane] = None,
    ):
        init_logging()
        self.config = config or RuntimeConfig.from_env()
        self._owns_store = store is None
        self.store = store if store is not None else make_store(self.config.store, self.config.store_path)
        self._event_plane = event_plane
        self._owns_event_plane = event_plane is None
        self.tcp_client = TcpClient()
        self._http_client = None  # lazy: most deployments never use it
        self.metrics = MetricsScope()
        # shared retry policies/breakers created after this point export
        # their counters through this runtime's registry (-> /metrics)
        from .resilience import adopt_metrics_scope

        adopt_metrics_scope(self.metrics)
        # supervised background work (runtime/tasks.py; reference
        # utils/tasks/tracker.rs): components spawn under runtime.tasks so
        # shutdown() drains the whole tree
        self.tasks = TaskTracker(name="runtime")
        self.lease_id: Optional[str] = None
        self._keepalive_task: Optional[asyncio.Task] = None
        self._started = False
        # ServedEndpoints register here so their instance keys can be re-put
        # if the lease is ever lost and re-acquired
        self.served: list = []

    @property
    def http_client(self):
        if self._http_client is None:
            from .request_plane.http import HttpClient

            self._http_client = HttpClient()
        return self._http_client

    async def start(self) -> "DistributedRuntime":
        if self._started:
            return self
        self._started = True
        lease = await self.store.create_lease(self.config.lease_ttl_s)
        self.lease_id = lease.id
        self._keepalive_task = asyncio.create_task(self._keepalive_loop(lease.ttl_s))
        if self._event_plane is None:
            if self.config.event_plane == "zmq":
                from .event_plane.zmq_plane import event_plane_from_store

                self._event_plane = await event_plane_from_store(self.store, self.lease_id)
            else:
                self._event_plane = InProcEventPlane()
        log.debug("runtime started (lease=%s, store=%s)", lease.id[:8], self.config.store)
        return self

    @property
    def event_plane(self) -> EventPlane:
        assert self._event_plane is not None, "runtime not started"
        return self._event_plane

    async def _keepalive_loop(self, ttl_s: float) -> None:
        interval = max(ttl_s / 3.0, 0.2)
        try:
            while True:
                await asyncio.sleep(interval)
                if self.lease_id is None:
                    continue
                try:
                    await FAULTS.ainject("discovery.lease_keepalive")
                    ok = await self.store.keep_alive(self.lease_id)
                except Exception as e:
                    # a raising heartbeat must not kill the loop — treat it
                    # as a missed beat and let the lease path recover
                    log.warning("lease keepalive error: %s", e)
                    ok = False
                if not ok:
                    log.warning("lease %s lost; re-acquiring", self.lease_id[:8])
                    try:
                        # shared policy (scope discovery.lease): the store
                        # may be mid-restart; back off instead of hot-looping
                        lease = await retry_policy(
                            "discovery.lease",
                            max_attempts=4, base_delay_s=0.1, max_delay_s=2.0,
                            retryable=(Exception,),
                        ).acall(self.store.create_lease, ttl_s)
                    except Exception:
                        log.exception(
                            "lease re-acquire failed; retrying next beat"
                        )
                        continue
                    self.lease_id = lease.id
                    # lease expiry deleted our instance keys: re-register
                    # every endpoint this runtime still serves
                    for served in list(self.served):
                        try:
                            await self.store.put_obj(
                                served._key, served.instance.to_obj(), self.lease_id
                            )
                            for k, obj in served.extra_objs.items():
                                await self.store.put_obj(k, obj, self.lease_id)
                        except Exception:
                            log.exception("re-register %s failed", served._key)
        except asyncio.CancelledError:
            pass

    async def shutdown(self) -> None:
        await self.tasks.graceful_shutdown(
            timeout=self.config.graceful_shutdown_timeout_s
        )
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
        if self.lease_id is not None:
            try:
                await self.store.revoke_lease(self.lease_id)
            except Exception:  # best effort during teardown
                pass
            self.lease_id = None
        if self._event_plane is not None and self._owns_event_plane:
            await self._event_plane.close()
        await self.tcp_client.close()
        if self._http_client is not None:
            await self._http_client.close()
        if self._owns_store:
            await self.store.close()
        self._started = False

    async def __aenter__(self) -> "DistributedRuntime":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.shutdown()


async def make_runtime(
    store_kind: Optional[str] = None,
    store_path: Optional[str] = None,
    event_plane: Optional[str] = None,
    shared_store: Optional[KVStore] = None,
) -> DistributedRuntime:
    cfg = RuntimeConfig.from_env(
        store=store_kind, store_path=store_path, event_plane=event_plane
    )
    rt = DistributedRuntime(cfg, store=shared_store)
    return await rt.start()
