"""Generic JSONL event recorder with rotation, limits, and replay.

Analog of the reference's ``Recorder<T>`` (lib/llm/src/recorder.rs): producers
send events to a queue; a background task streams them to a JSONL file as
``{"timestamp": <unix_ns>, "event": ...}`` lines, rotating at
``max_lines_per_file`` and shutting down after ``max_count`` events or
``max_time_s`` seconds. ``load()``/``replay()`` re-read a recording — the
standalone router records its ingested KV-event stream this way
(``python -m dynamo_tpu.router --record-events PATH``).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Any, AsyncIterator, List, Optional, Tuple

from .logging import get_logger

log = get_logger("recorder")


class Recorder:
    def __init__(
        self,
        output_path: str,
        max_lines_per_file: Optional[int] = None,
        max_count: Optional[int] = None,
        max_time_s: Optional[float] = None,
    ):
        self.output_path = output_path
        self.max_lines_per_file = max_lines_per_file
        self.max_count = max_count
        self.max_time_s = max_time_s
        self.event_count = 0
        self._file_index = 0
        self._lines_in_file = 0
        self._first_event_at: Optional[float] = None
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._stopped = asyncio.Event()

    # -- producer side --------------------------------------------------------
    def record(self, event: Any) -> bool:
        """Enqueue one event; False once limits hit (recorder draining)."""
        if self._stopped.is_set():
            return False
        self._queue.put_nowait(event)
        return True

    # -- lifecycle ------------------------------------------------------------
    async def start(self) -> "Recorder":
        self._task = asyncio.create_task(self._run())
        return self

    async def stop(self) -> None:
        self._stopped.set()
        self._queue.put_nowait(None)  # wake the writer
        if self._task is not None:
            await self._task

    def _path_for_index(self) -> str:
        if self._file_index == 0:
            return self.output_path
        base, ext = os.path.splitext(self.output_path)
        return f"{base}.{self._file_index}{ext}"

    async def _run(self) -> None:
        f = open(self._path_for_index(), "w")
        try:
            while True:
                if self._stopped.is_set() and self._queue.empty():
                    break
                try:
                    event = await asyncio.wait_for(self._queue.get(), timeout=0.25)
                except asyncio.TimeoutError:
                    f.flush()
                    if self._deadline_passed():
                        break
                    continue
                if event is None:
                    continue
                if self._first_event_at is None:
                    self._first_event_at = time.monotonic()
                f.write(json.dumps({"timestamp": time.time_ns(), "event": event}) + "\n")
                self.event_count += 1
                self._lines_in_file += 1
                if (
                    self.max_lines_per_file is not None
                    and self._lines_in_file >= self.max_lines_per_file
                ):
                    f.close()
                    self._file_index += 1
                    self._lines_in_file = 0
                    f = open(self._path_for_index(), "w")
                if self.max_count is not None and self.event_count >= self.max_count:
                    break
                if self._deadline_passed():
                    break
        finally:
            f.close()
            self._stopped.set()

    def _deadline_passed(self) -> bool:
        return (
            self.max_time_s is not None
            and self._first_event_at is not None
            and time.monotonic() - self._first_event_at >= self.max_time_s
        )

    # -- replay ---------------------------------------------------------------
    @staticmethod
    def load(path: str) -> List[Tuple[int, Any]]:
        """[(timestamp_ns, event), ...] from one recording file."""
        out: List[Tuple[int, Any]] = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                out.append((obj["timestamp"], obj["event"]))
        return out

    @staticmethod
    async def replay(
        path: str, speedup: float = 1.0
    ) -> AsyncIterator[Any]:
        """Yield events with their original pacing (scaled by ``speedup``)."""
        entries = Recorder.load(path)
        prev_ts: Optional[int] = None
        for ts, event in entries:
            if prev_ts is not None and speedup > 0:
                await asyncio.sleep((ts - prev_ts) / 1e9 / speedup)
            prev_ts = ts
            yield event
