"""G4 remote KV block tier: a shared content-addressed block store.

Analog of the reference's CacheLevel::G4 "Remote NVMe" (lib/llm/src/
block_manager.rs:63-77, reached via NIXL object/file backends): a standalone
block-store service many workers share, so a prefix prefilled by one worker
is onboardable by every other worker in the fleet even after it falls out of
their local tiers.

Protocol (framed TCP, msgpack header + raw block payload — same framing
philosophy as the request plane, but blocking sockets because tier calls run
on the engine's offload thread, never the event loop):

    {op: "store", hash: H, shape: [...], dtype: "float32"} + payload
    {op: "get", hash: H}        -> {ok, shape, dtype} + payload
    {op: "has", hashes: [...]}  -> {have: [bool, ...]}
    {op: "stats"}               -> {blocks, bytes, hits, misses}

The server (`python -m dynamo_tpu.kvbm.server`) keeps an LRU bounded by
--capacity-bytes, optionally persisting blocks under --disk PATH (that is
the actual "remote NVMe": RAM index over disk payloads).
"""

from __future__ import annotations

import asyncio
import os
import socket
import struct
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

import msgpack
import numpy as np

from ..runtime.logging import get_logger

log = get_logger("kvbm.remote")

_HDR = struct.Struct("!II")  # (header_len, payload_len)


def _pack(obj: dict, payload: bytes = b"") -> bytes:
    head = msgpack.packb(obj, use_bin_type=True)
    return _HDR.pack(len(head), len(payload)) + head + payload


async def _read_frame(reader: asyncio.StreamReader):
    raw = await reader.readexactly(_HDR.size)
    hlen, plen = _HDR.unpack(raw)
    head = msgpack.unpackb(await reader.readexactly(hlen), raw=False)
    payload = await reader.readexactly(plen) if plen else b""
    return head, payload


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("remote block store closed connection")
        buf += chunk
    return bytes(buf)


class RemoteBlockStoreServer:
    """The shared G4 service: content-addressed LRU of KV blocks."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        capacity_bytes: int = 1 << 31,
        disk_path: Optional[str] = None,
    ):
        self.host = host
        self.port = port
        self.capacity_bytes = capacity_bytes
        self.disk_path = disk_path
        if disk_path:
            os.makedirs(disk_path, exist_ok=True)
        # hash -> (shape, dtype, payload | None if on disk)
        self._blocks: OrderedDict[int, tuple] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()

    # -- storage helpers -----------------------------------------------------
    def _disk_file(self, h: int) -> str:
        return os.path.join(self.disk_path, f"{h:016x}.kv")

    def _evict_until(self, needed: int) -> None:
        while self._bytes + needed > self.capacity_bytes and self._blocks:
            victim, (shape, dtype, payload, nbytes) = self._blocks.popitem(last=False)
            self._bytes -= nbytes
            if self.disk_path:
                try:
                    os.unlink(self._disk_file(victim))
                except FileNotFoundError:
                    pass

    def _store(self, h: int, shape, dtype: str, payload: bytes) -> None:
        if h in self._blocks:
            self._blocks.move_to_end(h)
            return
        self._evict_until(len(payload))
        if self.disk_path:
            tmp = self._disk_file(h) + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, self._disk_file(h))
            self._blocks[h] = (shape, dtype, None, len(payload))
        else:
            self._blocks[h] = (shape, dtype, payload, len(payload))
        self._bytes += len(payload)

    def _get(self, h: int):
        entry = self._blocks.get(h)
        if entry is None:
            self.misses += 1
            return None
        self._blocks.move_to_end(h)
        shape, dtype, payload, nbytes = entry
        if payload is None:
            try:
                with open(self._disk_file(h), "rb") as f:
                    payload = f.read()
            except FileNotFoundError:
                self._blocks.pop(h, None)
                self._bytes -= nbytes
                self.misses += 1
                return None
        self.hits += 1
        return shape, dtype, payload

    # -- wire ----------------------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        try:
            while True:
                try:
                    head, payload = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                op = head.get("op")
                if op == "store":
                    self._store(head["hash"], head["shape"], head["dtype"], payload)
                    writer.write(_pack({"ok": True}))
                elif op == "get":
                    got = self._get(head["hash"])
                    if got is None:
                        writer.write(_pack({"ok": False}))
                    else:
                        shape, dtype, data = got
                        writer.write(_pack(
                            {"ok": True, "shape": list(shape), "dtype": dtype}, data
                        ))
                elif op == "has":
                    writer.write(_pack(
                        {"have": [h in self._blocks for h in head["hashes"]]}
                    ))
                elif op == "stats":
                    writer.write(_pack({
                        "blocks": len(self._blocks), "bytes": self._bytes,
                        "hits": self.hits, "misses": self.misses,
                    }))
                else:
                    writer.write(_pack({"ok": False, "error": f"bad op {op!r}"}))
                await writer.drain()
        finally:
            if task is not None:
                self._conn_tasks.discard(task)
            writer.close()

    async def start(self) -> str:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("remote block store listening on %s:%d", self.host, self.port)
        return f"{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # py3.12 wait_closed() blocks until every connection handler
            # returns, and pooled clients hold connections open — cancel them
            for t in list(self._conn_tasks):
                t.cancel()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                pass


class RemoteBlockPool:
    """G4 client used inside KvbmTiers: blocking socket per offload thread,
    reconnect-on-error, degrades to disabled after repeated failures."""

    def __init__(self, address: str, timeout_s: float = 5.0, max_failures: int = 3):
        host, port = address.rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.timeout_s = timeout_s
        self.max_failures = max_failures
        self._failures = 0
        self._local = threading.local()
        self._all_socks: set = set()  # every live socket across threads
        self._socks_lock = threading.Lock()
        self.disabled = False

    # -- socket plumbing -----------------------------------------------------
    def _sock(self) -> socket.socket:
        s = getattr(self._local, "sock", None)
        if s is None:
            s = socket.create_connection((self.host, self.port), timeout=self.timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._socks_lock:
                if self.disabled:  # close() raced us: don't leak a live conn
                    s.close()
                    raise ConnectionError("remote block pool closed")
                self._all_socks.add(s)
            self._local.sock = s
        return s

    def _drop_sock(self) -> None:
        s = getattr(self._local, "sock", None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass
            with self._socks_lock:
                self._all_socks.discard(s)
            self._local.sock = None

    def close(self) -> None:
        """Close every socket this pool ever opened, across all threads.

        Servers awaiting wait_closed() depend on clients dropping their
        connections — the same hang class the netstore fix (9634c67)
        addressed server-side; this is the client half.
        """
        with self._socks_lock:
            self.disabled = True
            socks, self._all_socks = self._all_socks, set()
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)  # wakes a recv blocked elsewhere
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def _call(self, obj: dict, payload: bytes = b""):
        if self.disabled:
            return None
        try:
            s = self._sock()
            s.sendall(_pack(obj, payload))
            hlen, plen = _HDR.unpack(_recv_exact(s, _HDR.size))
            head = msgpack.unpackb(_recv_exact(s, hlen), raw=False)
            data = _recv_exact(s, plen) if plen else b""
            self._failures = 0
            return head, data
        except (OSError, ConnectionError) as e:
            self._drop_sock()
            self._failures += 1
            if self._failures >= self.max_failures:
                self.disabled = True
                log.warning("remote block store unreachable (%r); G4 disabled", e)
            return None

    # -- tier interface ------------------------------------------------------
    def __contains__(self, h: int) -> bool:
        got = self._call({"op": "has", "hashes": [int(h)]})
        return bool(got and got[0]["have"][0])

    def contains_many(self, hashes: List[int]) -> List[bool]:
        got = self._call({"op": "has", "hashes": [int(h) for h in hashes]})
        return got[0]["have"] if got else [False] * len(hashes)

    def store(self, h: int, block: np.ndarray) -> None:
        block = np.ascontiguousarray(block)
        self._call(
            {"op": "store", "hash": int(h), "shape": list(block.shape),
             "dtype": str(block.dtype)},
            block.tobytes(),
        )

    def get(self, h: int) -> Optional[np.ndarray]:
        got = self._call({"op": "get", "hash": int(h)})
        if not got or not got[0].get("ok"):
            return None
        head, data = got
        return np.frombuffer(data, dtype=head["dtype"]).reshape(head["shape"]).copy()

    def stats(self) -> Dict[str, int]:
        got = self._call({"op": "stats"})
        return got[0] if got else {}
