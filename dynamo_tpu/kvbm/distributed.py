"""Distributed KVBM: a fleet of G4 block stores behind one pool interface.

Analog of the reference's block_manager/distributed (leader/worker): instead
of one shared remote store, N stores each hold a consistent-hash shard of
the content-addressed block space. Membership is LIVE — workers register in
the discovery store under ``v1/kvbm/{namespace}/`` with a lease, and every
client watches that prefix, so a crashed store drops out of the ring at
lease expiry and an added one takes its shard over immediately.

Correctness under churn is free: blocks are content-addressed, a re-routed
lookup that misses simply recomputes prefill (the same guarantee every tier
gives), and stores are populated by write-through so the new owner fills up
on first use.

The ring uses per-worker virtual nodes so shard sizes stay even at small
fleet sizes (the classic consistent-hash construction).
"""

from __future__ import annotations

import asyncio
import bisect
import hashlib
import threading
from typing import Dict, List, Optional

import numpy as np

from ..runtime.discovery.store import EventType, KVStore
from ..runtime.logging import get_logger
from .remote import RemoteBlockPool

log = get_logger("kvbm.distributed")

VNODES = 64


def fleet_key(namespace: str, address: str) -> str:
    return f"v1/kvbm/{namespace}/{address}"


def fleet_prefix(namespace: str) -> str:
    return f"v1/kvbm/{namespace}/"


async def register_store(
    store: KVStore, namespace: str, address: str, lease_id: Optional[str]
) -> None:
    """Worker side: announce this block store's address under a lease."""
    await store.put_obj(
        fleet_key(namespace, address), {"address": address}, lease_id
    )


class HashRing:
    def __init__(self):
        self._points: List[int] = []
        self._owner: Dict[int, str] = {}

    @staticmethod
    def _point(s: str) -> int:
        return int.from_bytes(hashlib.blake2b(s.encode(), digest_size=8).digest(), "big")

    def add(self, address: str) -> None:
        for v in range(VNODES):
            p = self._point(f"{address}#{v}")
            if p not in self._owner:
                bisect.insort(self._points, p)
                self._owner[p] = address

    def remove(self, address: str) -> None:
        for v in range(VNODES):
            p = self._point(f"{address}#{v}")
            if self._owner.get(p) == address:
                del self._owner[p]
                i = bisect.bisect_left(self._points, p)
                if i < len(self._points) and self._points[i] == p:
                    del self._points[i]

    def owner(self, h: int) -> Optional[str]:
        if not self._points:
            return None
        # mix the key before placement: content hashes SHOULD be uniform,
        # but adjacent/structured keys must not all land in one segment
        p = self._point(str(int(h)))
        i = bisect.bisect_right(self._points, p) % len(self._points)
        return self._owner[self._points[i]]

    def members(self) -> List[str]:
        return sorted(set(self._owner.values()))


class DistributedBlockPool:
    """Drop-in for RemoteBlockPool (same tier interface), sharded over the
    live fleet. Pass to KvbmTiers(remote=...)."""

    def __init__(self, store: KVStore, namespace: str = "dynamo"):
        self._store = store
        self.namespace = namespace
        self._ring = HashRing()
        self._pools: Dict[str, RemoteBlockPool] = {}
        self._lock = threading.Lock()
        self._watch_task: Optional[asyncio.Task] = None
        self.disabled = False  # interface parity with RemoteBlockPool

    async def start(self) -> "DistributedBlockPool":
        watcher = await self._store.watch(fleet_prefix(self.namespace))

        async def consume() -> None:
            async for ev in watcher:
                addr = ev.key.rsplit("/", 1)[-1]
                with self._lock:
                    if ev.type is EventType.PUT:
                        if addr not in self._pools:
                            log.info("kvbm fleet: + %s", addr)
                            self._ring.add(addr)
                            self._pools[addr] = RemoteBlockPool(addr)
                    else:
                        log.info("kvbm fleet: - %s", addr)
                        self._ring.remove(addr)
                        self._pools.pop(addr, None)

        self._watch_task = asyncio.create_task(consume())
        self._watcher = watcher
        return self

    async def stop(self) -> None:
        if self._watch_task is not None:
            self._watcher.cancel()
            self._watch_task.cancel()
        # Close member-client sockets so store servers' wait_closed() can
        # complete (client half of the netstore 9634c67 hang fix).
        with self._lock:
            pools = list(self._pools.values())
        for p in pools:
            p.close()

    # ------------------------------------------------------- tier interface
    def _pool_for(self, h: int) -> Optional[RemoteBlockPool]:
        with self._lock:
            addr = self._ring.owner(int(h))
            return self._pools.get(addr) if addr else None

    def __contains__(self, h: int) -> bool:
        p = self._pool_for(h)
        return bool(p and h in p)

    def contains_many(self, hashes: List[int]) -> List[bool]:
        # group by owner so each store answers one batched query
        by_pool: Dict[int, List[int]] = {}
        pools: Dict[int, RemoteBlockPool] = {}
        for i, h in enumerate(hashes):
            p = self._pool_for(h)
            if p is None:
                continue
            by_pool.setdefault(id(p), []).append(i)
            pools[id(p)] = p
        out = [False] * len(hashes)
        for pid, idxs in by_pool.items():
            have = pools[pid].contains_many([int(hashes[i]) for i in idxs])
            for i, got in zip(idxs, have):
                out[i] = bool(got)
        return out

    def store(self, h: int, block: np.ndarray) -> None:
        p = self._pool_for(h)
        if p is not None:
            p.store(h, block)

    def get(self, h: int) -> Optional[np.ndarray]:
        p = self._pool_for(h)
        return p.get(h) if p is not None else None

    def members(self) -> List[str]:
        with self._lock:
            return self._ring.members()
