"""Fleet-wide content-addressed KV block directory (ROADMAP item 3).

A global map ``content hash -> {worker, tier, dtype-format}`` living on the
discovery/netstore plane (runtime/discovery: MemKVStore in-proc and for the
sim, TcpKVStore across processes), maintained incrementally as workers seal,
offload and evict blocks — and torn down as drained workers checkpoint out.
On a local radix miss the router prices *onboard-from-peer-tier vs
recompute* (ops/costs.fetch_vs_recompute) and, when fetching wins, the
worker streams the blocks from the peer's G2/G3 tier over the block-window
protocol instead of re-prefilling (engine/transfer.py peer-tier pull).

Entry lifetime has two independent clocks:

- a **store lease** attached to every key this publisher writes: if the
  worker dies, lease expiry deletes its advertisements wholesale (etcd
  semantics; ``revoke_lease`` on orderly shutdown does the same
  synchronously);
- a per-entry ``ts`` stamp from an **injected clock**: lookups filter
  entries older than ``ttl_s`` so a store whose lease reaper runs on wall
  time (MemKVStore) still ages entries deterministically on the sim's
  virtual clock. ``refresh`` re-stamps the publisher's live set.

Dedupe: a hash already advertised by ``dedupe_replicas`` live holders is
not advertised again — identical sealed blocks across the fleet converge
to a bounded holder set instead of N copies of every hot prefix
(``dtpu_global_kv_dedup_blocks_total`` counts the skips).

Fetch leases: a fetch in flight holds a :class:`FetchLease` from
``begin_fetch`` that MUST reach ``commit_fetch`` or ``abort_fetch`` on
every path out — registered as a ResourceSpec (tools/analysis/resources.py
"fetch-lease") so RESOURCE-LEAK proves no failed fetch strands a lease.
Directory entries themselves are the store-shaped "directory-entry"
resource: owner-stored on publish, released by unpublish, with lease
expiry as the structural backstop.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from ..runtime import metrics as M
from ..runtime.config import (
    ENV_GLOBAL_KV,
    ENV_GLOBAL_KV_DEDUPE,
    ENV_GLOBAL_KV_FETCH_MARGIN,
    ENV_GLOBAL_KV_TTL_S,
    env_bool,
    env_float,
    env_int,
)
from ..runtime.faults import FAULTS
from ..runtime.logging import get_logger
from ..tokens import SequenceHash

log = get_logger("kvbm.directory")

# key layout: <prefix><hash:016x>/<holder> -> msgpack entry
DEFAULT_PREFIX = "kvdir/"
DEFAULT_TTL_S = 120.0
DEFAULT_DEDUPE_REPLICAS = 2
DEFAULT_FETCH_MARGIN = 1.0


def directory_enabled() -> bool:
    """Master switch (docs/operations.md 'Fleet-wide KV reuse')."""
    return env_bool(ENV_GLOBAL_KV, False)


def directory_ttl_s() -> float:
    return env_float(ENV_GLOBAL_KV_TTL_S, DEFAULT_TTL_S)


def directory_dedupe_replicas() -> int:
    return max(1, env_int(ENV_GLOBAL_KV_DEDUPE, DEFAULT_DEDUPE_REPLICAS))


def fetch_margin() -> float:
    """``fetch <= margin * recompute`` decision bound (ops/costs.py)."""
    return env_float(ENV_GLOBAL_KV_FETCH_MARGIN, DEFAULT_FETCH_MARGIN)


@dataclasses.dataclass(frozen=True)
class DirectoryEntry:
    """One advertisement: ``holder`` serves ``hash`` from ``tier`` in
    ``fmt`` ("model" float bytes or "int8" codec buffers) at ``address``
    (its KV-transfer endpoint)."""

    hash: int
    holder: str
    tier: str            # "g2" | "g3"
    fmt: str             # "model" | "int8"
    address: str
    ts: float


@dataclasses.dataclass
class FetchLease:
    """An in-flight peer-tier fetch. Must be discharged via
    :meth:`GlobalKvDirectory.commit_fetch` or :meth:`abort_fetch` on every
    path out of the fetching function (RESOURCE-LEAK "fetch-lease")."""

    token: int
    holder: str
    hashes: List[int]
    started_at: float


class GlobalKvDirectory:
    """One worker's client on the shared directory plane.

    ``store`` is any runtime/discovery KVStore; ``holder`` is this
    publisher's fleet-unique identity (worker id, or "pool/wid" in the
    sim); ``clock`` injects time for deterministic ts aging (defaults to
    ``time.monotonic``)."""

    def __init__(
        self,
        store,
        holder: str,
        *,
        address: str = "",
        ttl_s: Optional[float] = None,
        dedupe_replicas: Optional[int] = None,
        prefix: str = DEFAULT_PREFIX,
        clock: Optional[Callable[[], float]] = None,
        metrics=None,
    ):
        self.store = store
        self.holder = str(holder)
        self.address = address
        self.ttl_s = float(ttl_s if ttl_s is not None else directory_ttl_s())
        self.dedupe_replicas = int(
            dedupe_replicas if dedupe_replicas is not None
            else directory_dedupe_replicas()
        )
        self.prefix = prefix
        self.clock = clock or time.monotonic
        self._lease_id: Optional[str] = None
        # hashes this publisher currently advertises (the "directory-entry"
        # resource's owner attribute: stored == advertised)
        self._published: Dict[int, str] = {}   # hash -> tier
        self._fetch_token = 0
        self._fetches: Dict[int, FetchLease] = {}
        self.dedupe_skipped = 0
        self._m_hits = self._m_entries = self._m_dedup = None
        if metrics is not None:
            self._m_hits = metrics.counter(
                M.GLOBAL_KV_HITS_TOTAL,
                "fleet-level prefix-miss resolutions by outcome",
                extra_labels=("outcome",),
            )
            self._m_entries = metrics.gauge(
                M.GLOBAL_KV_DIRECTORY_ENTRIES,
                "directory entries this worker currently advertises",
            )
            self._m_dedup = metrics.counter(
                M.GLOBAL_KV_DEDUP_BLOCKS_TOTAL,
                "publishes skipped because enough holders already advertise",
            )

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> "GlobalKvDirectory":
        """Create the store lease the advertisements ride on: a dead
        worker's entries age out with it (keep_alive from the runtime's
        normal heartbeat keeps them live)."""
        lease = await self.store.create_lease(max(self.ttl_s, 1.0))
        self._lease_id = lease.id
        return self

    async def keep_alive(self) -> bool:
        if self._lease_id is None:
            return False
        return await self.store.keep_alive(self._lease_id)

    async def close(self) -> None:
        """Orderly shutdown (drain/checkpoint-out): revoke the lease, which
        deletes every advertisement this worker wrote in one call."""
        if self._lease_id is not None:
            try:
                await self.store.revoke_lease(self._lease_id)
            except Exception:
                log.warning("directory lease revoke failed", exc_info=True)
            self._lease_id = None
        elif self._published:
            # lease-less client (sim): nothing deletes the keys for us
            try:
                await self.withdraw_all()
            except Exception:
                log.warning("directory withdraw failed", exc_info=True)
        self._published.clear()
        if self._m_entries is not None:
            self._m_entries.set(0)

    # -- publish / unpublish -------------------------------------------------
    def _key(self, h: int, holder: Optional[str] = None) -> str:
        return f"{self.prefix}{int(h) & ((1 << 64) - 1):016x}/{holder or self.holder}"

    def _live(self, entries: Iterable[DirectoryEntry]) -> List[DirectoryEntry]:
        now = self.clock()
        return [e for e in entries if now - e.ts <= self.ttl_s]

    async def publish(
        self, hashes: Sequence[SequenceHash], tier: str, fmt: str = "model",
    ) -> int:
        """Advertise sealed blocks this worker can serve from ``tier``.
        Returns the number actually written; hashes already advertised by
        ``dedupe_replicas`` other live holders are skipped (dedupe)."""
        await FAULTS.ainject("directory.publish")
        wrote = 0
        for h in hashes:
            h = int(h)
            prev = self._published.get(h)
            if prev == tier:
                continue
            if prev is None and self.dedupe_replicas > 0:
                others = [
                    e for e in await self._lookup_raw(h)
                    if e.holder != self.holder
                ]
                if len(others) >= self.dedupe_replicas:
                    self.dedupe_skipped += 1
                    if self._m_dedup is not None:
                        self._m_dedup.inc()
                    continue
            await self.store.put_obj(
                self._key(h),
                {
                    "tier": tier, "fmt": fmt, "address": self.address,
                    "ts": float(self.clock()),
                },
                lease_id=self._lease_id,
            )
            self._published[h] = tier
            wrote += 1
        if self._m_entries is not None:
            self._m_entries.set(len(self._published))
        return wrote

    async def unpublish(self, hashes: Sequence[SequenceHash]) -> int:
        """Withdraw advertisements (eviction from every local tier, or a
        drained worker checkpointing out)."""
        dropped = 0
        for h in hashes:
            h = int(h)
            if self._published.pop(h, None) is None:
                continue
            await self.store.delete(self._key(h))
            dropped += 1
        if self._m_entries is not None:
            self._m_entries.set(len(self._published))
        return dropped

    async def withdraw_all(self) -> int:
        """Delete every advertisement this client wrote — the lease-less
        analog of :meth:`close` (a drained worker checkpointing out, or an
        orderly sim scale-down)."""
        return await self.unpublish(list(self._published))

    async def refresh(self) -> int:
        """Re-stamp every live advertisement's ``ts`` (periodic, alongside
        the lease keep-alive) so held blocks outlive the entry ttl."""
        for h, tier in list(self._published.items()):
            await self.store.put_obj(
                self._key(h),
                {
                    "tier": tier, "fmt": "model", "address": self.address,
                    "ts": float(self.clock()),
                },
                lease_id=self._lease_id,
            )
        return len(self._published)

    @property
    def published_count(self) -> int:
        return len(self._published)

    # -- lookup --------------------------------------------------------------
    async def _lookup_raw(self, h: int) -> List[DirectoryEntry]:
        base = f"{self.prefix}{int(h) & ((1 << 64) - 1):016x}/"
        out: List[DirectoryEntry] = []
        for key, obj in (await self.store.list_obj(base)).items():
            if not isinstance(obj, dict):
                continue
            out.append(DirectoryEntry(
                hash=int(h),
                holder=key[len(base):],
                tier=str(obj.get("tier", "g2")),
                fmt=str(obj.get("fmt", "model")),
                address=str(obj.get("address", "")),
                ts=float(obj.get("ts", 0.0)),
            ))
        return self._live(out)

    async def lookup(self, h: SequenceHash) -> List[DirectoryEntry]:
        """Live holders of one hash (stale ``ts`` filtered; deterministic
        holder order)."""
        await FAULTS.ainject("directory.lookup")
        return sorted(await self._lookup_raw(int(h)), key=lambda e: e.holder)

    async def lookup_run(
        self, hashes: Sequence[SequenceHash], exclude_holder: Optional[str] = None,
    ) -> List[DirectoryEntry]:
        """The longest contiguous leading run of ``hashes`` fetchable from
        a SINGLE holder (one wire, one stream — the fetch planner's unit).
        The holder serving the first hash with the longest continuation
        wins; ties break by holder id for determinism."""
        await FAULTS.ainject("directory.lookup")
        if not hashes:
            return []
        first = await self._lookup_raw(int(hashes[0]))
        best: List[DirectoryEntry] = []
        for head in sorted(first, key=lambda e: e.holder):
            if exclude_holder is not None and head.holder == exclude_holder:
                continue
            run = [head]
            for h in hashes[1:]:
                nxt = [
                    e for e in await self._lookup_raw(int(h))
                    if e.holder == head.holder
                ]
                if not nxt:
                    break
                run.append(nxt[0])
            if len(run) > len(best):
                best = run
        return best

    # -- fetch leases (RESOURCE-LEAK "fetch-lease") --------------------------
    def begin_fetch(
        self, holder: str, hashes: Sequence[SequenceHash],
    ) -> FetchLease:
        """Open a fetch lease for an onboard-from-peer attempt. The caller
        MUST route it to :meth:`commit_fetch` (blocks imported) or
        :meth:`abort_fetch` (fetch failed -> recompute) on every path."""
        self._fetch_token += 1
        lease = FetchLease(
            token=self._fetch_token, holder=str(holder),
            hashes=[int(h) for h in hashes], started_at=float(self.clock()),
        )
        self._fetches[lease.token] = lease
        return lease

    def commit_fetch(self, lease: FetchLease, imported_blocks: int) -> None:
        self._fetches.pop(lease.token, None)
        self.record_outcome("fetched")

    def abort_fetch(self, lease: FetchLease) -> None:
        self._fetches.pop(lease.token, None)
        self.record_outcome("recomputed")

    @property
    def inflight_fetches(self) -> int:
        return len(self._fetches)

    def record_outcome(self, outcome: str) -> None:
        """Count one fleet-miss resolution (outcome: fetched|recomputed)."""
        if self._m_hits is not None:
            self._m_hits.inc(outcome=outcome)
