"""Block memory layouts: how one KV block's bytes are organized in a tier.

Analog of the reference's layout abstraction
(lib/llm/src/block_manager/layout.rs, FullyContiguous vs LayerSeparate):
the LOGICAL block is always [num_layers, 2, block_size, kv_heads, head_dim]
(K and V per layer), but tiers and transfer agents care about the physical
arrangement:

- **FullyContiguous** — one C-order buffer per block. What the wire formats
  and the disk tier want: a block is a single read/write.
- **LayerSeparate** — one buffer per layer (outer dim peeled off). What the
  DEVICE side produces and consumes: engine gathers/scatters are per-layer
  (k_caches/v_caches are per-layer arrays), so layer-separate storage avoids
  the [L, ...] -> [n, L, ...] transpose copy on every offload.

Both layouts expose the same views so tiers can store either way and
transfer code can convert only when crossing a boundary.

``BlockShape.dtype`` is the STORAGE dtype and has no default: callers must
derive it from the model (``block_shape_for``) — the old np.float32 default
silently made bf16 models pay 2x host-RAM and wire bytes per block. With
``kv_dtype="int8"`` the storage format is int8 payload + per-layer-per-K/V
per-kv-head f32 scales, and ``QuantizedBlockCodec`` packs the pair into ONE
flat uint8 buffer so every tier (host dict, disk file, remote store, native
arena) keeps treating a block as a single opaque byte run.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np

from ..ops.quant import SCALE_DTYPE


@dataclasses.dataclass(frozen=True)
class BlockShape:
    num_layers: int
    block_size: int
    num_kv_heads: int
    head_dim: int
    dtype: np.dtype

    @property
    def logical_shape(self) -> Tuple[int, int, int, int, int]:
        return (self.num_layers, 2, self.block_size, self.num_kv_heads,
                self.head_dim)

    @property
    def layer_shape(self) -> Tuple[int, int, int, int]:
        return (2, self.block_size, self.num_kv_heads, self.head_dim)

    @property
    def nbytes(self) -> int:
        n = 1
        for d in self.logical_shape:
            n *= d
        return n * self.dtype.itemsize

    @property
    def layer_nbytes(self) -> int:
        return self.nbytes // self.num_layers


class FullyContiguous:
    """One buffer per block, logical C-order."""

    def __init__(self, shape: BlockShape):
        self.shape = shape

    def pack(self, per_layer: Sequence[np.ndarray]) -> np.ndarray:
        """[2, bs, kvh, d] x L -> one [L, 2, bs, kvh, d] buffer."""
        assert len(per_layer) == self.shape.num_layers
        return np.stack([np.asarray(p) for p in per_layer]).astype(
            self.shape.dtype, copy=False
        )

    def unpack(self, block: np.ndarray) -> List[np.ndarray]:
        block = block.reshape(self.shape.logical_shape)
        return [block[i] for i in range(self.shape.num_layers)]

    def layer_view(self, block: np.ndarray, layer: int) -> np.ndarray:
        return block.reshape(self.shape.logical_shape)[layer]

    def to_bytes(self, block: np.ndarray) -> bytes:
        return np.ascontiguousarray(block).tobytes()

    def from_bytes(self, raw: bytes) -> np.ndarray:
        return np.frombuffer(raw, self.shape.dtype).reshape(
            self.shape.logical_shape
        )


class LayerSeparate:
    """One buffer per layer: matches the engine's per-layer cache arrays, so
    device-side gathers land here without an extra stack/transpose."""

    def __init__(self, shape: BlockShape):
        self.shape = shape

    def pack(self, per_layer: Sequence[np.ndarray]) -> List[np.ndarray]:
        assert len(per_layer) == self.shape.num_layers
        return [
            np.ascontiguousarray(np.asarray(p), dtype=self.shape.dtype)
            for p in per_layer
        ]

    def unpack(self, block: List[np.ndarray]) -> List[np.ndarray]:
        return list(block)

    def layer_view(self, block: List[np.ndarray], layer: int) -> np.ndarray:
        return block[layer]

    def to_bytes(self, block: List[np.ndarray]) -> bytes:
        return b"".join(np.ascontiguousarray(p).tobytes() for p in block)

    def from_bytes(self, raw: bytes) -> List[np.ndarray]:
        n = self.shape.layer_nbytes
        return [
            np.frombuffer(raw[i * n:(i + 1) * n], self.shape.dtype).reshape(
                self.shape.layer_shape
            )
            for i in range(self.shape.num_layers)
        ]


def convert(block, src, dst):
    """Re-layout one block (copy only when crossing representations)."""
    if type(src) is type(dst):
        return block
    return dst.pack(src.unpack(block)) if isinstance(dst, LayerSeparate) else (
        np.stack(src.unpack(block))
    )


def make_layout(kind: str, shape: BlockShape):
    if kind in ("contiguous", "fully_contiguous", "fc"):
        return FullyContiguous(shape)
    if kind in ("layer_separate", "ls"):
        return LayerSeparate(shape)
    raise ValueError(f"unknown layout {kind!r}")


def dtype_from_name(name: str) -> np.dtype:
    """np.dtype('bfloat16') is only resolvable through ml_dtypes — the one
    name->dtype spot for block storage (disk tier headers, wire fields)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def block_shape_for(mcfg, block_size: int, kv_dtype: str = "model") -> BlockShape:
    """THE constructor for KV block shapes: storage dtype comes from the
    model config (bf16 models store bf16 blocks), or int8 for the quantized
    cache. Allocating a KV buffer with a raw np.float32 elsewhere is a lint
    finding (tools/lint.py KV-DTYPE)."""
    dtype = np.dtype(np.int8) if kv_dtype == "int8" else np.dtype(mcfg.dtype)
    return BlockShape(
        num_layers=mcfg.num_layers,
        block_size=block_size,
        num_kv_heads=mcfg.num_kv_heads,
        head_dim=mcfg.head_dim,
        dtype=dtype,
    )


class QuantizedBlockCodec:
    """int8 block <-> one flat uint8 buffer (payload then scales).

    Logical quantized block:
      payload [L, 2, bs, kvh, d] int8
      scales  [L, 2, kvh]        f32  (per layer, per K/V, per kv head)

    encode/decode are pure byte moves — bit-exact round-trips by
    construction, which is what lets transfer/KVBM ship quantized blocks
    without ever touching the floats. ``shape.dtype`` must be int8."""

    def __init__(self, shape: BlockShape):
        assert shape.dtype == np.dtype(np.int8), shape
        self.shape = shape
        self.payload_shape = shape.logical_shape
        self.scales_shape = (shape.num_layers, 2, shape.num_kv_heads)
        self.payload_nbytes = int(np.prod(self.payload_shape))
        self.scales_nbytes = (
            int(np.prod(self.scales_shape)) * SCALE_DTYPE.itemsize
        )
        self.nbytes = self.payload_nbytes + self.scales_nbytes

    def encode(self, payload: np.ndarray, scales: np.ndarray) -> np.ndarray:
        """(payload [L,2,bs,kvh,d] int8, scales [L,2,kvh] f32) -> uint8 [nbytes]."""
        buf = np.empty(self.nbytes, np.uint8)
        buf[: self.payload_nbytes] = np.ascontiguousarray(
            payload.reshape(self.payload_shape).view(np.int8)
        ).view(np.uint8).reshape(-1)
        buf[self.payload_nbytes:] = np.ascontiguousarray(
            np.asarray(scales, SCALE_DTYPE).reshape(self.scales_shape)
        ).view(np.uint8).reshape(-1)
        return buf

    def decode(self, buf: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """uint8 [nbytes] -> (payload, scales). Zero-copy views."""
        flat = np.asarray(buf, np.uint8).reshape(-1)
        payload = flat[: self.payload_nbytes].view(np.int8).reshape(
            self.payload_shape
        )
        scales = flat[self.payload_nbytes:].view(SCALE_DTYPE).reshape(
            self.scales_shape
        )
        return payload, scales

    def decode_many(self, bufs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """uint8 [n, nbytes] -> (payload [n, L, 2, ...], scales [n, L, 2, kvh])."""
        n = bufs.shape[0]
        flat = np.ascontiguousarray(bufs, dtype=np.uint8).reshape(n, -1)
        payload = flat[:, : self.payload_nbytes].view(np.int8).reshape(
            (n,) + self.payload_shape
        )
        scales = np.ascontiguousarray(
            flat[:, self.payload_nbytes:]
        ).view(SCALE_DTYPE).reshape((n,) + self.scales_shape)
        return payload, scales


def kv_bytes_per_token(mcfg, block_size: int, kv_dtype: str = "model") -> float:
    """KV bytes one token occupies in the paged cache — the SAME number for
    HBM, the transfer wire, and a KVBM tier block, since all three store the
    identical format (block_shape_for / QuantizedBlockCodec). int8 amortizes
    the per-block scale rows over block_size positions; at d=64, bs=16 that
    lands ~0.51x of bf16 (the bench emits this so the win is measurable)."""
    shape = block_shape_for(mcfg, block_size, kv_dtype)
    if kv_dtype == "int8":
        return QuantizedBlockCodec(shape).nbytes / block_size
    return shape.nbytes / block_size
