"""Block memory layouts: how one KV block's bytes are organized in a tier.

Analog of the reference's layout abstraction
(lib/llm/src/block_manager/layout.rs, FullyContiguous vs LayerSeparate):
the LOGICAL block is always [num_layers, 2, block_size, kv_heads, head_dim]
(K and V per layer), but tiers and transfer agents care about the physical
arrangement:

- **FullyContiguous** — one C-order buffer per block. What the wire formats
  and the disk tier want: a block is a single read/write.
- **LayerSeparate** — one buffer per layer (outer dim peeled off). What the
  DEVICE side produces and consumes: engine gathers/scatters are per-layer
  (k_caches/v_caches are per-layer arrays), so layer-separate storage avoids
  the [L, ...] -> [n, L, ...] transpose copy on every offload.

Both layouts expose the same views so tiers can store either way and
transfer code can convert only when crossing a boundary.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class BlockShape:
    num_layers: int
    block_size: int
    num_kv_heads: int
    head_dim: int
    dtype: np.dtype = np.dtype(np.float32)

    @property
    def logical_shape(self) -> Tuple[int, int, int, int, int]:
        return (self.num_layers, 2, self.block_size, self.num_kv_heads,
                self.head_dim)

    @property
    def layer_shape(self) -> Tuple[int, int, int, int]:
        return (2, self.block_size, self.num_kv_heads, self.head_dim)

    @property
    def nbytes(self) -> int:
        n = 1
        for d in self.logical_shape:
            n *= d
        return n * self.dtype.itemsize

    @property
    def layer_nbytes(self) -> int:
        return self.nbytes // self.num_layers


class FullyContiguous:
    """One buffer per block, logical C-order."""

    def __init__(self, shape: BlockShape):
        self.shape = shape

    def pack(self, per_layer: Sequence[np.ndarray]) -> np.ndarray:
        """[2, bs, kvh, d] x L -> one [L, 2, bs, kvh, d] buffer."""
        assert len(per_layer) == self.shape.num_layers
        return np.stack([np.asarray(p) for p in per_layer]).astype(
            self.shape.dtype, copy=False
        )

    def unpack(self, block: np.ndarray) -> List[np.ndarray]:
        block = block.reshape(self.shape.logical_shape)
        return [block[i] for i in range(self.shape.num_layers)]

    def layer_view(self, block: np.ndarray, layer: int) -> np.ndarray:
        return block.reshape(self.shape.logical_shape)[layer]

    def to_bytes(self, block: np.ndarray) -> bytes:
        return np.ascontiguousarray(block).tobytes()

    def from_bytes(self, raw: bytes) -> np.ndarray:
        return np.frombuffer(raw, self.shape.dtype).reshape(
            self.shape.logical_shape
        )


class LayerSeparate:
    """One buffer per layer: matches the engine's per-layer cache arrays, so
    device-side gathers land here without an extra stack/transpose."""

    def __init__(self, shape: BlockShape):
        self.shape = shape

    def pack(self, per_layer: Sequence[np.ndarray]) -> List[np.ndarray]:
        assert len(per_layer) == self.shape.num_layers
        return [
            np.ascontiguousarray(np.asarray(p), dtype=self.shape.dtype)
            for p in per_layer
        ]

    def unpack(self, block: List[np.ndarray]) -> List[np.ndarray]:
        return list(block)

    def layer_view(self, block: List[np.ndarray], layer: int) -> np.ndarray:
        return block[layer]

    def to_bytes(self, block: List[np.ndarray]) -> bytes:
        return b"".join(np.ascontiguousarray(p).tobytes() for p in block)

    def from_bytes(self, raw: bytes) -> List[np.ndarray]:
        n = self.shape.layer_nbytes
        return [
            np.frombuffer(raw[i * n:(i + 1) * n], self.shape.dtype).reshape(
                self.shape.layer_shape
            )
            for i in range(self.shape.num_layers)
        ]


def convert(block, src, dst):
    """Re-layout one block (copy only when crossing representations)."""
    if type(src) is type(dst):
        return block
    return dst.pack(src.unpack(block)) if isinstance(dst, LayerSeparate) else (
        np.stack(src.unpack(block))
    )


def make_layout(kind: str, shape: BlockShape):
    if kind in ("contiguous", "fully_contiguous", "fc"):
        return FullyContiguous(shape)
    if kind in ("layer_separate", "ls"):
        return LayerSeparate(shape)
    raise ValueError(f"unknown layout {kind!r}")
