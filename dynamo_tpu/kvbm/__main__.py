"""python -m dynamo_tpu.kvbm — standalone G4 remote block-store service.

The fleet-shared KV tier (reference CacheLevel::G4 "Remote NVMe",
lib/llm/src/block_manager.rs:63-77): workers point at it with
``--kvbm-remote HOST:PORT`` and a prefix prefilled anywhere becomes
onboardable everywhere.
"""

import argparse
import asyncio
import signal

from dynamo_tpu.kvbm.remote import RemoteBlockStoreServer
from dynamo_tpu.runtime import init_logging


def parse_args():
    p = argparse.ArgumentParser("dynamo_tpu.kvbm")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7440)
    p.add_argument("--capacity-gb", type=float, default=2.0)
    p.add_argument("--disk", default=None,
                   help="persist block payloads under this directory "
                        "(RAM index over disk payloads)")
    p.add_argument("--store", default=None,
                   help="register in this discovery store to join the "
                        "DISTRIBUTED kvbm fleet (kvbm/distributed.py): "
                        "clients consistent-hash blocks across members")
    p.add_argument("--store-path", default=None)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--advertise", default=None,
                   help="address to register (default: host:port with the "
                        "bound port)")
    return p.parse_args()


async def main() -> None:
    args = parse_args()
    init_logging()
    server = RemoteBlockStoreServer(
        host=args.host, port=args.port,
        capacity_bytes=int(args.capacity_gb * (1 << 30)),
        disk_path=args.disk,
    )
    addr = await server.start()
    runtime = None
    if args.store:
        from dynamo_tpu.kvbm.distributed import register_store
        from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

        runtime = await DistributedRuntime(
            RuntimeConfig.from_env(store=args.store, store_path=args.store_path)
        ).start()
        advertise = args.advertise or addr
        await register_store(
            runtime.store, args.namespace, advertise, runtime.lease_id
        )
        print(f"KVBM_FLEET_MEMBER {advertise}", flush=True)
    print(f"KVBM_REMOTE_READY {addr}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await server.stop()
    if runtime is not None:
        await runtime.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
