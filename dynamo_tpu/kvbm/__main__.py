"""python -m dynamo_tpu.kvbm — standalone G4 remote block-store service.

The fleet-shared KV tier (reference CacheLevel::G4 "Remote NVMe",
lib/llm/src/block_manager.rs:63-77): workers point at it with
``--kvbm-remote HOST:PORT`` and a prefix prefilled anywhere becomes
onboardable everywhere.
"""

import argparse
import asyncio
import signal

from dynamo_tpu.kvbm.remote import RemoteBlockStoreServer
from dynamo_tpu.runtime import init_logging


def parse_args():
    p = argparse.ArgumentParser("dynamo_tpu.kvbm")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7440)
    p.add_argument("--capacity-gb", type=float, default=2.0)
    p.add_argument("--disk", default=None,
                   help="persist block payloads under this directory "
                        "(RAM index over disk payloads)")
    return p.parse_args()


async def main() -> None:
    args = parse_args()
    init_logging()
    server = RemoteBlockStoreServer(
        host=args.host, port=args.port,
        capacity_bytes=int(args.capacity_gb * (1 << 30)),
        disk_path=args.disk,
    )
    addr = await server.start()
    print(f"KVBM_REMOTE_READY {addr}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await server.stop()


if __name__ == "__main__":
    asyncio.run(main())
