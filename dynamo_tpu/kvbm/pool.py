"""Multi-tier KV block pools: G2 host DRAM, G3 local disk, G4 remote.

Analog of the reference's KVBM block manager (lib/llm/src/block_manager:
G1 device / G2 host / G3 disk / G4 remote, block_manager.rs:63-77) built for
the TPU engine: sealed device blocks are written through to a host pool
asynchronously; host overflow spills to disk; a prefix lookup that misses HBM
onboards from host/disk/remote back into device pages before prefill. The G4
remote tier (kvbm/remote.py) is a fleet-shared block store.

Offload ordering follows the reference's priority-queue design
(lib/llm/src/block_manager/offload.rs:4-34): offloads enqueue with a
priority; lower values transfer first, FIFO within a priority, and the
bounded queue sheds the lowest-priority work under backpressure instead of
stalling the engine.

Storage layout per block: one contiguous buffer per block (a single memcpy
for the host copy, a single file write for disk). The BYTES are whatever the
engine's KV storage format is (kvbm/layout.block_shape_for): model-dtype
[L, 2, bs, kvh, d] for float caches — bf16 models store bf16, not a 2x
float32 blow-up — or the flat int8+scales codec buffer for kv_dtype="int8",
which halves host-RAM and wire bytes per block again. The pools themselves
are format-agnostic.
"""

from __future__ import annotations

import heapq
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..runtime.logging import get_logger
from ..tokens import SequenceHash

log = get_logger("kvbm")


class HostBlockPool:
    """G2: content-addressed host DRAM pool with LRU eviction."""

    def __init__(self, capacity_bytes: int, block_nbytes: int):
        self.capacity_blocks = max(0, capacity_bytes // max(block_nbytes, 1))
        self.block_nbytes = block_nbytes
        self._data: OrderedDict[SequenceHash, np.ndarray] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def __contains__(self, h: SequenceHash) -> bool:
        with self._lock:
            return h in self._data

    def __len__(self) -> int:
        return len(self._data)

    def store(self, h: SequenceHash, block: np.ndarray) -> Optional[Tuple[SequenceHash, np.ndarray]]:
        """Insert; returns an evicted (hash, block) for spillover, if any."""
        if self.capacity_blocks == 0:
            return (h, block)
        evicted = None
        with self._lock:
            if h in self._data:
                self._data.move_to_end(h)
                return None
            if len(self._data) >= self.capacity_blocks:
                evicted = self._data.popitem(last=False)
            self._data[h] = block
        return evicted

    def get(self, h: SequenceHash) -> Optional[np.ndarray]:
        with self._lock:
            block = self._data.get(h)
            if block is not None:
                self._data.move_to_end(h)
                self.hits += 1
            else:
                self.misses += 1
            return block

    def drop(self, h: SequenceHash) -> None:
        with self._lock:
            self._data.pop(h, None)

    def clear(self) -> List[SequenceHash]:
        """Drop every block; returns the evicted hashes (controller reset,
        reference block_manager/controller.rs cache-level commands)."""
        with self._lock:
            gone = list(self._data)
            self._data.clear()
        return gone


# G3 file format: 4-byte little-endian header length, json {"dtype","shape"},
# raw C-order bytes. np.save cannot round-trip ml_dtypes (a saved bfloat16
# block loads back as void '|V2' and poisons onboarding), so the dtype rides
# an explicit header resolved via layout.dtype_from_name. Legacy .npy files
# (pre-header spill dirs survive restarts) are still readable.
_NPY_MAGIC = b"\x93NUMPY"


def _write_block_file(path: str, block: np.ndarray) -> None:
    import json as _json

    header = _json.dumps(
        {"dtype": block.dtype.name, "shape": list(block.shape)}
    ).encode()
    with open(path, "wb") as f:
        f.write(len(header).to_bytes(4, "little"))
        f.write(header)
        f.write(np.ascontiguousarray(block).tobytes())


def _read_block_file(path: str) -> np.ndarray:
    import json as _json

    from .layout import dtype_from_name

    with open(path, "rb") as f:
        head = f.read(4)
        if head[:4].startswith(_NPY_MAGIC[:4]):
            # legacy np.save file from an older spill dir
            f.seek(0)
            return np.load(f, allow_pickle=False)
        n = int.from_bytes(head, "little")
        meta = _json.loads(f.read(n))
        data = f.read()
    return np.frombuffer(data, dtype_from_name(meta["dtype"])).reshape(
        meta["shape"]
    )


class DiskBlockPool:
    """G3: one file per block under a spill directory, LRU by access order."""

    def __init__(self, path: str, capacity_bytes: int, block_nbytes: int):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.capacity_blocks = max(0, capacity_bytes // max(block_nbytes, 1))
        self._lru: OrderedDict[SequenceHash, None] = OrderedDict()
        self._lock = threading.Lock()
        # recover existing blocks (warm restart: the disk tier survives)
        for name in sorted(os.listdir(path)):
            if name.endswith(".kv"):
                try:
                    self._lru[int(name[:-3], 16)] = None
                except ValueError:
                    pass

    def _file(self, h: SequenceHash) -> str:
        return os.path.join(self.path, f"{h:016x}.kv")

    def __contains__(self, h: SequenceHash) -> bool:
        with self._lock:
            return h in self._lru

    def __len__(self) -> int:
        return len(self._lru)

    def store(self, h: SequenceHash, block: np.ndarray) -> List[SequenceHash]:
        """Insert; returns hashes evicted from disk (gone for good)."""
        if self.capacity_blocks == 0:
            return [h]
        gone: List[SequenceHash] = []
        with self._lock:
            if h in self._lru:
                self._lru.move_to_end(h)
                return gone
            while len(self._lru) >= self.capacity_blocks:
                victim, _ = self._lru.popitem(last=False)
                gone.append(victim)
                try:
                    os.unlink(self._file(victim))
                except FileNotFoundError:
                    pass
        tmp = self._file(h) + f".tmp{os.getpid()}"
        _write_block_file(tmp, block)
        os.replace(tmp, self._file(h))
        with self._lock:
            self._lru[h] = None
        return gone

    def get(self, h: SequenceHash) -> Optional[np.ndarray]:
        with self._lock:
            if h not in self._lru:
                return None
            self._lru.move_to_end(h)
        try:
            return _read_block_file(self._file(h))
        except (FileNotFoundError, ValueError, KeyError):
            with self._lock:
                self._lru.pop(h, None)
            return None

    def clear(self) -> List[SequenceHash]:
        """Drop every block and its spill file (controller reset). Unlinks
        happen under the lock: a concurrent offload-worker store() re-writing
        one of these hashes must either complete before the snapshot (file
        deleted, hash reported gone) or after the clear (fresh file, fresh
        LRU entry) — never lose a freshly re-stored block's file."""
        with self._lock:
            gone = list(self._lru)
            self._lru.clear()
            for h in gone:
                try:
                    os.unlink(self._file(h))
                except FileNotFoundError:
                    pass
        return gone


class OffloadQueue:
    """Bounded priority queue feeding one offload worker thread.

    Reference analog: OffloadManager's priority queue (offload.rs:10-16) —
    lower priority value first, FIFO within a priority (monotone sequence
    number breaks ties). When full, the LOWEST-priority queued item is shed
    (never the incoming one if it outranks something queued): bandwidth is
    the scarce resource and the most reusable blocks should win it."""

    def __init__(self, max_items: int = 512):
        self.max_items = max_items
        self._heap: List[tuple] = []  # (priority, seq, hash, block)
        self._seq = 0
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self.shed = 0
        self.in_flight = 0  # popped but not yet written (flush waits on this)
        self._closed = False

    def put(self, h: SequenceHash, block: np.ndarray, priority: int) -> None:
        with self._ready:
            if self._closed:
                return
            heapq.heappush(self._heap, (priority, self._seq, h, block))
            self._seq += 1
            if len(self._heap) > self.max_items:
                # shed the worst item: max priority, newest within it
                worst = max(range(len(self._heap)), key=lambda i: (
                    self._heap[i][0], self._heap[i][1]
                ))
                self._heap[worst] = self._heap[-1]
                self._heap.pop()
                heapq.heapify(self._heap)
                self.shed += 1
            self._ready.notify()

    def get(self, timeout: Optional[float] = None):
        with self._ready:
            if not self._heap:
                self._ready.wait(timeout)
            if not self._heap:
                return None
            self.in_flight += 1
            return heapq.heappop(self._heap)

    def task_done(self) -> None:
        with self._lock:
            self.in_flight = max(0, self.in_flight - 1)

    def close(self) -> None:
        with self._ready:
            self._closed = True
            self._ready.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)


class KvbmTiers:
    """G2+G3+G4 stack with prioritized write-through offload and prefix
    onboarding (G4 = kvbm/remote.py RemoteBlockPool, or anything with the
    same store/get/__contains__ surface)."""

    def __init__(
        self,
        block_nbytes: int,
        host_capacity_bytes: int = 1 << 30,
        disk_capacity_bytes: int = 0,
        disk_path: str = "/tmp/dtpu_kvbm",
        remote=None,
        offload_queue_depth: int = 512,
    ):
        self.host = HostBlockPool(host_capacity_bytes, block_nbytes)
        self.disk = (
            DiskBlockPool(disk_path, disk_capacity_bytes, block_nbytes)
            if disk_capacity_bytes > 0
            else None
        )
        self.remote = remote
        self.offloaded = 0
        self.onboarded = 0
        # hashes evicted from every tier since the last drain (the engine
        # turns these into router 'removed' events so the index stays honest)
        self._evicted: List[SequenceHash] = []
        self._evicted_lock = threading.Lock()
        # hashes newly written to a local tier since the last drain — the
        # engine turns these into global-directory advertisements
        # (kvbm/directory.py); same consolidated cadence as _evicted
        self._stored: List[SequenceHash] = []
        self.queue = OffloadQueue(offload_queue_depth)
        self._worker: Optional[threading.Thread] = None

    def __contains__(self, h: SequenceHash) -> bool:
        # LOCAL tiers only: a remote round-trip per hash would put RPCs on
        # whatever thread asks; remote membership is batched (match_prefix,
        # filter_servable)
        return h in self.host or (self.disk is not None and h in self.disk)

    def _insert_host(self, h: SequenceHash, block: np.ndarray) -> None:
        """Host insert with spill-to-disk; tracks blocks gone from all tiers."""
        evicted = self.host.store(h, block)
        if evicted is None:
            return
        if self.disk is not None:
            gone = self.disk.store(*evicted)
        else:
            gone = [evicted[0]]
        if gone:
            with self._evicted_lock:
                self._evicted.extend(gone)

    def store(self, h: SequenceHash, block: np.ndarray) -> None:
        """Synchronous write-through (host + remote). Prefer ``offload``."""
        self._insert_host(h, block)
        if self.remote is not None:
            self.remote.store(h, block)
        self.offloaded += 1
        with self._evicted_lock:
            self._stored.append(h)

    # -- prioritized async offload (offload.rs analog) -----------------------
    def offload(self, h: SequenceHash, block: np.ndarray, priority: int = 1) -> None:
        """Enqueue a block for background write-through; lower priority value
        transfers first. The engine uses priority 0 for prompt-prefix blocks
        (highest reuse odds) and 1 for decode-sealed blocks."""
        self._ensure_worker()
        self.queue.put(h, block, priority)

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._offload_loop, name="kvbm-offload", daemon=True
            )
            self._worker.start()

    def _offload_loop(self) -> None:
        while True:
            item = self.queue.get(timeout=1.0)
            if item is None:
                if self.queue._closed:
                    return
                continue
            _prio, _seq, h, block = item
            try:
                self.store(h, block)
            except Exception:
                log.exception("kvbm offload of block %x failed", h)
            finally:
                self.queue.task_done()

    def flush(self, timeout_s: float = 10.0) -> None:
        """Wait until the offload queue drains (tests / orderly shutdown)."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while (
            (len(self.queue) or self.queue.in_flight)
            and _time.monotonic() < deadline
        ):
            _time.sleep(0.005)

    def close(self) -> None:
        self.queue.close()

    def drain_evicted(self) -> List[SequenceHash]:
        with self._evicted_lock:
            out, self._evicted = self._evicted, []
        return out

    def drain_stored(self) -> List[SequenceHash]:
        """Hashes newly offloaded to a local tier since the last drain
        (directory advertisement feed)."""
        with self._evicted_lock:
            out, self._stored = self._stored, []
        return out

    def clear(self, host: bool = True, disk: bool = True) -> Dict[str, int]:
        """Controller reset of local tiers (G2/G3). Evicted hashes feed the
        normal consolidated-event path (drain_evicted), so the router only
        learns 'removed' for blocks no longer servable from ANY tier."""
        counts = {"g2": 0, "g3": 0}
        gone: List[SequenceHash] = []
        if host:
            dropped = self.host.clear()
            counts["g2"] = len(dropped)
            gone.extend(dropped)
        if disk and self.disk is not None:
            dropped = self.disk.clear()
            counts["g3"] = len(dropped)
            gone.extend(dropped)
        if gone:
            with self._evicted_lock:
                self._evicted.extend(gone)
        return counts

    def tier_of(self, h: SequenceHash) -> Optional[str]:
        """Which LOCAL tier holds ``h`` ("g2" host, "g3" disk), or None.
        Feeds the global KV directory's tier advertisements."""
        if h in self.host:
            return "g2"
        if self.disk is not None and h in self.disk:
            return "g3"
        return None

    def get_block(
        self, h: SequenceHash
    ) -> Optional[Tuple[np.ndarray, str]]:
        """Read ONE block from a local tier WITHOUT G3->G2 promotion —
        serving a peer's fetch must not churn this worker's host LRU on the
        peer's behalf. Returns (block, tier) or None."""
        b = self.host.get(h)
        if b is not None:
            return b, "g2"
        if self.disk is not None:
            b = self.disk.get(h)
            if b is not None:
                return b, "g3"
        return None

    def filter_servable(self, hashes: List[SequenceHash]) -> List[SequenceHash]:
        """Subset of ``hashes`` still servable from ANY tier (remote queried
        in one batch). Used to consolidate router 'removed' events."""
        local = [h for h in hashes if h in self]
        rest = [h for h in hashes if h not in self]
        if rest and self.remote is not None:
            have = self.remote.contains_many(rest)
            local.extend(h for h, ok in zip(rest, have) if ok)
        return local

    def match_prefix(self, hashes: List[SequenceHash]) -> int:
        n = 0
        for h in hashes:
            if h in self:
                n += 1
            else:
                break
        if n < len(hashes) and self.remote is not None:
            # extend the contiguous run from the fleet-shared tier
            have = self.remote.contains_many(hashes[n:])
            for ok in have:
                if not ok:
                    break
                n += 1
        return n

    def load_prefix(self, hashes: List[SequenceHash]) -> Optional[np.ndarray]:
        """Contiguous blocks [n, L, 2, bs, kvh, d] (or [n, nbytes] codec
        buffers) for a matched prefix. The run stops at the first block whose
        shape/dtype differs from the first: a restart-surviving disk tier or
        shared remote store can hold blocks written under a different
        kv_dtype for the same content hashes, and stacking mixed formats
        would raise instead of degrading to a shorter onboard (the engine's
        format guard then vets what remains)."""
        blocks = []
        for h in hashes:
            b = self.host.get(h)
            if b is None and self.disk is not None:
                b = self.disk.get(h)
            if b is None and self.remote is not None:
                b = self.remote.get(h)
            if b is None:
                break
            if blocks and (
                b.shape != blocks[0].shape or b.dtype != blocks[0].dtype
            ):
                log.warning(
                    "kvbm block %x format %s%s != prefix %s%s; truncating "
                    "onboard run", h, b.dtype, b.shape,
                    blocks[0].dtype, blocks[0].shape,
                )
                break
            if h not in self.host:
                self._insert_host(h, b)  # promote G3/G4 -> G2 (with spill)
            blocks.append(b)
        if not blocks:
            return None
        self.onboarded += len(blocks)
        return np.stack(blocks)

    def stats(self) -> Dict[str, int]:
        return {
            "host_blocks": len(self.host),
            "disk_blocks": len(self.disk) if self.disk is not None else 0,
            "host_hits": self.host.hits,
            "host_misses": self.host.misses,
            "offloaded": self.offloaded,
            "onboarded": self.onboarded,
            "queue_depth": len(self.queue),
            "queue_shed": self.queue.shed,
        }
