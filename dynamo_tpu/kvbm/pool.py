"""Multi-tier KV block pools: G2 host DRAM and G3 local disk.

Analog of the reference's KVBM block manager (lib/llm/src/block_manager:
G1 device / G2 host / G3 disk / G4 remote, block_manager.rs:63-77) built for
the TPU engine: sealed device blocks are written through to a host pool
asynchronously; host overflow spills to disk; a prefix lookup that misses HBM
onboards from host/disk back into device pages before prefill.

Storage layout per block: float32 array [L, 2, bs, kvh, d] (same shape the
transfer plane uses) — one contiguous buffer per block keeps the host copy
a single memcpy and the disk tier a single file write.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..runtime.logging import get_logger
from ..tokens import SequenceHash

log = get_logger("kvbm")


class HostBlockPool:
    """G2: content-addressed host DRAM pool with LRU eviction."""

    def __init__(self, capacity_bytes: int, block_nbytes: int):
        self.capacity_blocks = max(0, capacity_bytes // max(block_nbytes, 1))
        self.block_nbytes = block_nbytes
        self._data: OrderedDict[SequenceHash, np.ndarray] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def __contains__(self, h: SequenceHash) -> bool:
        with self._lock:
            return h in self._data

    def __len__(self) -> int:
        return len(self._data)

    def store(self, h: SequenceHash, block: np.ndarray) -> Optional[Tuple[SequenceHash, np.ndarray]]:
        """Insert; returns an evicted (hash, block) for spillover, if any."""
        if self.capacity_blocks == 0:
            return (h, block)
        evicted = None
        with self._lock:
            if h in self._data:
                self._data.move_to_end(h)
                return None
            if len(self._data) >= self.capacity_blocks:
                evicted = self._data.popitem(last=False)
            self._data[h] = block
        return evicted

    def get(self, h: SequenceHash) -> Optional[np.ndarray]:
        with self._lock:
            block = self._data.get(h)
            if block is not None:
                self._data.move_to_end(h)
                self.hits += 1
            else:
                self.misses += 1
            return block

    def drop(self, h: SequenceHash) -> None:
        with self._lock:
            self._data.pop(h, None)


class DiskBlockPool:
    """G3: one file per block under a spill directory, LRU by access order."""

    def __init__(self, path: str, capacity_bytes: int, block_nbytes: int):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.capacity_blocks = max(0, capacity_bytes // max(block_nbytes, 1))
        self._lru: OrderedDict[SequenceHash, None] = OrderedDict()
        self._lock = threading.Lock()
        # recover existing blocks (warm restart: the disk tier survives)
        for name in sorted(os.listdir(path)):
            if name.endswith(".kv"):
                try:
                    self._lru[int(name[:-3], 16)] = None
                except ValueError:
                    pass

    def _file(self, h: SequenceHash) -> str:
        return os.path.join(self.path, f"{h:016x}.kv")

    def __contains__(self, h: SequenceHash) -> bool:
        with self._lock:
            return h in self._lru

    def __len__(self) -> int:
        return len(self._lru)

    def store(self, h: SequenceHash, block: np.ndarray) -> List[SequenceHash]:
        """Insert; returns hashes evicted from disk (gone for good)."""
        if self.capacity_blocks == 0:
            return [h]
        gone: List[SequenceHash] = []
        with self._lock:
            if h in self._lru:
                self._lru.move_to_end(h)
                return gone
            while len(self._lru) >= self.capacity_blocks:
                victim, _ = self._lru.popitem(last=False)
                gone.append(victim)
                try:
                    os.unlink(self._file(victim))
                except FileNotFoundError:
                    pass
        tmp = self._file(h) + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.save(f, block, allow_pickle=False)
        os.replace(tmp, self._file(h))
        with self._lock:
            self._lru[h] = None
        return gone

    def get(self, h: SequenceHash) -> Optional[np.ndarray]:
        with self._lock:
            if h not in self._lru:
                return None
            self._lru.move_to_end(h)
        try:
            with open(self._file(h), "rb") as f:
                return np.load(f, allow_pickle=False)
        except (FileNotFoundError, ValueError):
            with self._lock:
                self._lru.pop(h, None)
            return None


class KvbmTiers:
    """G2+G3 stack with write-through offload and prefix onboarding."""

    def __init__(
        self,
        block_nbytes: int,
        host_capacity_bytes: int = 1 << 30,
        disk_capacity_bytes: int = 0,
        disk_path: str = "/tmp/dtpu_kvbm",
    ):
        self.host = HostBlockPool(host_capacity_bytes, block_nbytes)
        self.disk = (
            DiskBlockPool(disk_path, disk_capacity_bytes, block_nbytes)
            if disk_capacity_bytes > 0
            else None
        )
        self.offloaded = 0
        self.onboarded = 0
        # hashes evicted from every tier since the last drain (the engine
        # turns these into router 'removed' events so the index stays honest)
        self._evicted: List[SequenceHash] = []
        self._evicted_lock = threading.Lock()

    def __contains__(self, h: SequenceHash) -> bool:
        return h in self.host or (self.disk is not None and h in self.disk)

    def _insert_host(self, h: SequenceHash, block: np.ndarray) -> None:
        """Host insert with spill-to-disk; tracks blocks gone from all tiers."""
        evicted = self.host.store(h, block)
        if evicted is None:
            return
        if self.disk is not None:
            gone = self.disk.store(*evicted)
        else:
            gone = [evicted[0]]
        if gone:
            with self._evicted_lock:
                self._evicted.extend(gone)

    def store(self, h: SequenceHash, block: np.ndarray) -> None:
        self._insert_host(h, block)
        self.offloaded += 1

    def drain_evicted(self) -> List[SequenceHash]:
        with self._evicted_lock:
            out, self._evicted = self._evicted, []
        return out

    def match_prefix(self, hashes: List[SequenceHash]) -> int:
        n = 0
        for h in hashes:
            if h in self:
                n += 1
            else:
                break
        return n

    def load_prefix(self, hashes: List[SequenceHash]) -> Optional[np.ndarray]:
        """Contiguous blocks [n, L, 2, bs, kvh, d] for a matched prefix."""
        blocks = []
        for h in hashes:
            b = self.host.get(h)
            if b is None and self.disk is not None:
                b = self.disk.get(h)
                if b is not None:
                    self._insert_host(h, b)  # promote G3 -> G2 (with spill)
            if b is None:
                break
            blocks.append(b)
        if not blocks:
            return None
        self.onboarded += len(blocks)
        return np.stack(blocks)

    def stats(self) -> Dict[str, int]:
        return {
            "host_blocks": len(self.host),
            "disk_blocks": len(self.disk) if self.disk is not None else 0,
            "host_hits": self.host.hits,
            "host_misses": self.host.misses,
            "offloaded": self.offloaded,
            "onboarded": self.onboarded,
        }
